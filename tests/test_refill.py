"""Continuous batching: lane-identity conservation, splice bit-parity,
and the refill engine's ledger guarantees (tier-1, CPU; -m serve).

The load-bearing property: ANY interleaving of retire/splice over a
seeded schedule keeps the ``PCGResult.origin`` → request-id mapping
exact, and every member's iterate values bit-identical to an unrefilled
solve of the same member — per-member independence plus
chunk-invariance, the two facts that make in-flight refill sound.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.obs import metrics
from poisson_tpu.solvers.lanes import LaneBatch
from poisson_tpu.solvers.pcg import FLAG_CONVERGED, pcg_solve

pytestmark = pytest.mark.serve

PROBLEM = Problem(M=32, N=32)


@pytest.fixture(autouse=True)
def _fresh_registry():
    yield
    metrics.reset()


# -- solver layer: LaneBatch ------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_any_retire_splice_interleaving_is_bit_exact(seed):
    """Property-style: a seeded random schedule of splices (whenever a
    lane is free, with random reluctance) and retires (whenever a lane
    is done) over a 3-lane program must (a) never let two lanes carry
    the same member, (b) attribute every retired result to the exact
    member id spliced, and (c) reproduce the sequential solver's
    iterates bit-for-bit for EVERY member, no matter where in another
    member's flight it was spliced in."""
    rng = random.Random(seed)
    gates = {f"req-{i}": 1.0 + i / 7 for i in range(8)}
    golden = {mid: pcg_solve(PROBLEM, dtype="float32", rhs_gate=g)
              for mid, g in gates.items()}
    lb = LaneBatch(PROBLEM, bucket=3, dtype="float32",
                   chunk=rng.choice([3, 7, 11]))
    queue = list(gates)
    results = {}
    for _ in range(2000):
        if len(results) == len(gates):
            break
        for view in lb.lane_view():
            if view["member_id"] is not None and view["done"]:
                res = lb.retire(view["lane"])
                assert res.member_id == view["member_id"]
                results[res.member_id] = res
        while queue and lb.free_lanes() and rng.random() < 0.7:
            lb.splice(queue[0], gates[queue.pop(0)])
        occupied = [m for m in lb.origin if m is not None]
        assert len(occupied) == len(set(occupied))
        lb.step()
    assert len(results) == len(gates), "schedule did not drain"
    for mid, res in results.items():
        ref = golden[mid]
        assert res.iterations == int(ref.iterations), mid
        assert res.flag == int(ref.flag) == FLAG_CONVERGED, mid
        assert np.array_equal(np.asarray(res.w), np.asarray(ref.w)), (
            f"member {mid} drifted from its unrefilled solve")


def test_mid_flight_splice_does_not_perturb_the_resident_member():
    """The core splice soundness claim, isolated: a member 2 chunks deep
    when another splices in next to it finishes bit-identical to its
    solo solve — and so does the late joiner."""
    lb = LaneBatch(PROBLEM, bucket=2, dtype="float32", chunk=10)
    lb.splice("early", 1.0)
    lb.step()
    lb.step()                       # "early" is 20 iterations in
    lb.splice("late", 1.5)
    results = {}
    for _ in range(50):
        lb.step()
        for view in lb.lane_view():
            if view["member_id"] is not None and view["done"]:
                res = lb.retire(view["lane"])
                results[res.member_id] = res
        if not lb.occupied():
            break
    for mid, gate in (("early", 1.0), ("late", 1.5)):
        ref = pcg_solve(PROBLEM, dtype="float32", rhs_gate=gate)
        assert results[mid].iterations == int(ref.iterations)
        assert np.array_equal(np.asarray(results[mid].w),
                              np.asarray(ref.w))


def test_step_budget_is_per_lane_not_global():
    """A freshly spliced lane gets its own ``chunk`` iterations even
    when its neighbours are deep into theirs: stop_at is relative to
    each lane's carried k."""
    lb = LaneBatch(PROBLEM, bucket=2, dtype="float32", chunk=10)
    lb.splice("a", 1.0)
    lb.step()
    lb.splice("b", 1.2)
    lb.step()
    view = {v["member_id"]: v for v in lb.lane_view()}
    assert view["a"]["k"] == 20
    assert view["b"]["k"] == 10


def test_lane_occupancy_errors():
    lb = LaneBatch(PROBLEM, bucket=2, dtype="float32")
    lb.splice("a", 1.0, lane=0)
    with pytest.raises(ValueError, match="already occupies"):
        lb.splice("a", 1.0)
    with pytest.raises(ValueError, match="ACTIVE"):
        lb.splice("b", 1.0, lane=0)
    with pytest.raises(ValueError, match="EMPTY lane"):
        lb.splice(None, 1.0)
    with pytest.raises(ValueError, match="already EMPTY"):
        lb.retire(1)
    lb.splice("b", 1.0)
    with pytest.raises(ValueError, match="no EMPTY lane"):
        lb.splice("c", 1.0)
    with pytest.raises(ValueError):
        LaneBatch(PROBLEM, bucket=0)
    with pytest.raises(ValueError):
        LaneBatch(PROBLEM, bucket=2, chunk=0)


# -- service layer: the continuous engine ------------------------------


def _quiet():
    from poisson_tpu.serve import DegradationPolicy

    return DegradationPolicy(shrink_padding_at=9.0, cap_iterations_at=9.0,
                             downshift_precision_at=9.0)


def _service(scheduling, **kw):
    from poisson_tpu.serve import ServicePolicy, SolveService
    from poisson_tpu.testing.chaos import VirtualClock

    vc = VirtualClock()
    kw.setdefault("degradation", _quiet())
    svc = SolveService(
        ServicePolicy(scheduling=scheduling, **kw),
        clock=vc, sleep=vc.sleep, seed=0,
    )
    return svc, vc


def test_continuous_and_drain_agree_on_outcomes():
    """Same six requests through both engines: identical converged set
    and identical per-request iteration counts — scheduling must change
    wall-clock shape, never answers."""
    from poisson_tpu.serve import SCHED_CONTINUOUS, SCHED_DRAIN, SolveRequest

    per_mode = {}
    for mode in (SCHED_DRAIN, SCHED_CONTINUOUS):
        svc, _ = _service(mode, max_batch=4, refill_chunk=10)
        for i in range(6):
            svc.submit(SolveRequest(request_id=i, problem=PROBLEM,
                                    rhs_gate=1.0 + i / 10,
                                    dtype="float32"))
        outs = svc.drain()
        assert svc.stats()["lost"] == 0
        per_mode[mode] = {o.request_id: (o.converged, o.iterations)
                          for o in outs}
    assert per_mode[SCHED_DRAIN] == per_mode[SCHED_CONTINUOUS]
    assert all(c for c, _ in per_mode[SCHED_CONTINUOUS].values())


def test_open_loop_arrival_joins_mid_flight():
    """The pump() seam: request 0 is two chunks deep when 1 and 2 are
    submitted — they must splice into the running program (no new table)
    and every ledger entry must close."""
    from poisson_tpu.serve import SCHED_CONTINUOUS, SolveRequest

    svc, _ = _service(SCHED_CONTINUOUS, max_batch=4, refill_chunk=10)
    svc.submit(SolveRequest(request_id=0, problem=PROBLEM,
                            dtype="float32"))
    svc.pump()
    svc.pump()
    table = svc._table
    assert table is not None and table.occupied()
    for i in (1, 2):
        svc.submit(SolveRequest(request_id=i, problem=PROBLEM,
                                rhs_gate=1.0 + i / 10, dtype="float32"))
    outs = svc.drain()
    assert svc._table is table or svc._table is None  # no rebuild race
    assert sorted(o.request_id for o in outs) == [0, 1, 2]
    assert all(o.converged for o in outs)
    assert metrics.get("serve.refill.splices") == 3
    assert metrics.get("serve.refill.retired_lanes") == 3
    assert svc.stats()["lost"] == 0


def test_continuous_iterations_match_solo_solves():
    """Identity + trajectory conservation at the service level: each
    outcome's iteration count equals the sequential solve of the same
    rhs_gate, after riding lanes through refills."""
    from poisson_tpu.serve import SCHED_CONTINUOUS, SolveRequest

    gates = {i: 1.0 + i / 9 for i in range(7)}
    svc, _ = _service(SCHED_CONTINUOUS, max_batch=2, refill_chunk=15)
    for i, g in gates.items():
        svc.submit(SolveRequest(request_id=i, problem=PROBLEM,
                                rhs_gate=g, dtype="float32"))
    outs = {o.request_id: o for o in svc.drain()}
    for i, g in gates.items():
        ref = pcg_solve(PROBLEM, dtype="float32", rhs_gate=g)
        assert outs[i].converged
        assert outs[i].iterations == int(ref.iterations)


def test_ledger_is_honest_mid_flight():
    """stats() between pump() calls — the documented open-loop reading —
    must count a lane-resident request as pending, never as lost."""
    from poisson_tpu.serve import SCHED_CONTINUOUS, SolveRequest

    svc, _ = _service(SCHED_CONTINUOUS, max_batch=2, refill_chunk=10)
    svc.submit(SolveRequest(request_id="r", problem=PROBLEM,
                            dtype="float32"))
    svc.pump()                      # "r" is in a lane, mid-flight
    s = svc.stats()
    assert s["pending"] == 1
    assert s["lost"] == 0
    svc.drain()
    s = svc.stats()
    assert s["pending"] == 0 and s["lost"] == 0 and s["completed"] == 1


def test_scheduling_policy_validation():
    from poisson_tpu.serve import ServicePolicy, SolveService

    with pytest.raises(ValueError, match="scheduling"):
        SolveService(ServicePolicy(scheduling="sometimes"))
    with pytest.raises(ValueError, match="refill_chunk"):
        SolveService(ServicePolicy(refill_chunk=0))


# -- regression sentinel: metric directions ----------------------------


def _regress():
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from benchmarks import regress

    return regress


def _serve_rec(regress, metric, value, rate=80.0, fault="clean"):
    return regress.record_from_result(
        {"metric": metric, "value": value,
         "detail": {"grid": [96, 144], "dtype": "float32",
                    "backend": "xla_serve", "devices": 1,
                    "platform": "cpu", "fault_load": fault,
                    "arrival_rate": rate}},
        source=f"t:{metric}:{value}:{rate}:{fault}",
    )


def test_regress_pins_sustained_higher_is_better():
    regress = _regress()
    base = [_serve_rec(regress, "serve.sustained_solves_per_sec", v)
            for v in (60.0, 62.0, 61.0)]
    drop = regress.evaluate(
        base + [_serve_rec(regress, "serve.sustained_solves_per_sec",
                           30.0)])
    assert drop["verdict"] == "regression"
    rise = regress.evaluate(
        base + [_serve_rec(regress, "serve.sustained_solves_per_sec",
                           120.0)])
    assert rise["verdict"] == "ok"


def test_regress_pins_p99_lower_is_better():
    regress = _regress()
    base = [_serve_rec(regress, "serve.p99_latency", v, rate=None)
            for v in (0.2, 0.21, 0.19)]
    grew = regress.evaluate(
        base + [_serve_rec(regress, "serve.p99_latency", 0.5,
                           rate=None)])
    assert grew["verdict"] == "regression"
    shrank = regress.evaluate(
        base + [_serve_rec(regress, "serve.p99_latency", 0.05,
                           rate=None)])
    assert shrank["verdict"] == "ok"


def test_regress_splits_cohorts_by_arrival_rate_and_fault_load():
    """A sustained-throughput record at one offered load (or fault mix)
    must never be judged against another's baseline: with no same-rate
    sibling it is ``no_baseline``, not a regression."""
    regress = _regress()
    records = [
        _serve_rec(regress, "serve.sustained_solves_per_sec", 60.0,
                   rate=80.0),
        _serve_rec(regress, "serve.sustained_solves_per_sec", 61.0,
                   rate=80.0),
        # Far lower value, but a different arrival rate — own cohort.
        _serve_rec(regress, "serve.sustained_solves_per_sec", 10.0,
                   rate=200.0),
        # Same rate, different fault mix — own cohort as well.
        _serve_rec(regress, "serve.sustained_solves_per_sec", 9.0,
                   rate=80.0, fault="poison2"),
    ]
    report = regress.evaluate(records)
    assert report["verdict"] == "ok"
    cls = {v["source"]: v["classification"] for v in report["records"]}
    assert cls["t:serve.sustained_solves_per_sec:10.0:200.0:clean"] == \
        "no_baseline"
    assert cls["t:serve.sustained_solves_per_sec:9.0:80.0:poison2"] == \
        "no_baseline"
