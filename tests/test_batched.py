"""Batched multi-RHS driver (``solvers.batched``): batch-vs-sequential
bit-parity, per-member convergence masking, bucketing, and the CLI/bench
throughput surfaces.

The load-bearing property is the first one: ``solve_batched`` is a
*hardware batching* transform, not a numerical change, so each member's
iterates, flags, and iteration counts must match ``pcg_solve`` of the same
problem bit-for-bit — including members that converge early and sit frozen
while stragglers keep iterating (their post-freeze state must be exactly
their sequential final state).
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.obs import metrics
from poisson_tpu.solvers.batched import (
    DEFAULT_BUCKETS,
    bucket_size,
    reset_bucket_cache,
    solve_batched,
)
from poisson_tpu.solvers.pcg import FLAG_CONVERGED, pcg_solve

pytestmark = pytest.mark.batched


@pytest.fixture(autouse=True)
def _fresh_bucket_cache():
    """Counter assertions (hits/misses) must not depend on which bucket
    shapes earlier tests — or an earlier in-process run — already traced:
    the traced-shapes set and the metrics registry move together."""
    reset_bucket_cache()
    yield
    reset_bucket_cache()

# Distinct RHS magnitudes → distinct convergence trajectories (δ is an
# absolute threshold), so early convergers genuinely freeze while the
# largest-gate member keeps iterating.
GATES = (0.25, 1.0, 4.0)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.float32])
def test_batch_matches_sequential_bit_for_bit(dtype):
    p = Problem(M=40, N=40)
    seq = [pcg_solve(p, dtype=dtype, rhs_gate=g) for g in GATES]
    bat = solve_batched(p, rhs_gates=GATES, dtype=dtype)

    iters = np.asarray(bat.iterations)
    assert iters.shape == (len(GATES),)
    # The gates must actually spread the counts — otherwise the masked
    # freeze is never exercised and this test proves nothing.
    assert len({int(k) for k in iters}) >= 2
    for i, r in enumerate(seq):
        assert int(iters[i]) == int(r.iterations)
        assert int(np.asarray(bat.flag)[i]) == int(r.flag) == FLAG_CONVERGED
        # Bit-for-bit, not allclose: the batched member ran the exact
        # sequential iterate sequence and then froze.
        np.testing.assert_array_equal(np.asarray(bat.w)[i],
                                      np.asarray(r.w))
        assert float(np.asarray(bat.diff)[i]) == float(r.diff)
        assert float(np.asarray(bat.residual_dot)[i]) == float(
            r.residual_dot)
    assert int(bat.max_iterations) == max(int(r.iterations) for r in seq)


def test_problem_sequence_form_matches_sequential():
    base = Problem(M=30, N=30)
    problems = [base, base.with_(f_val=2.0), base.with_(f_val=0.5)]
    seq = [pcg_solve(p) for p in problems]
    bat = solve_batched(problems)
    for i, r in enumerate(seq):
        assert int(np.asarray(bat.iterations)[i]) == int(r.iterations)
        np.testing.assert_array_equal(np.asarray(bat.w)[i],
                                      np.asarray(r.w))


def test_rhs_stack_form_solves_distinct_rhs():
    p = Problem(M=30, N=30)
    from poisson_tpu.models.fictitious_domain import build_fields

    _, _, rhs = build_fields(p, dtype=np.float64, xp=np)
    stack = np.stack([rhs, 2.0 * rhs])
    bat = solve_batched(p, rhs_stack=stack)
    assert np.asarray(bat.iterations).shape == (2,)
    assert all(int(f) == FLAG_CONVERGED for f in np.asarray(bat.flag))
    # Solutions are distinct (different RHS) and finite.
    w = np.asarray(bat.w)
    assert np.isfinite(w).all()
    assert not np.array_equal(w[0], w[1])


def test_rhs_stack_shape_validated():
    p = Problem(M=30, N=30)
    with pytest.raises(ValueError, match="rhs_stack must be"):
        solve_batched(p, rhs_stack=np.zeros((2, 10, 10)))


def test_bucket_padding_is_invisible_and_counted():
    p = Problem(M=20, N=20)
    metrics.reset()
    bat = solve_batched(p, rhs_gates=(1.0, 2.0, 0.5))   # buckets to 4
    assert np.asarray(bat.iterations).shape == (3,)
    assert np.asarray(bat.w).shape[0] == 3
    assert metrics.get("batched.bucket_cache.misses") == 1
    assert metrics.get("batched.padding_members") == 1
    assert metrics.get("batched.solves") == 3
    # Same bucket again (different batch size, same executable): a hit.
    solve_batched(p, rhs_gates=(1.0, 2.0, 0.5, 3.0))
    assert metrics.get("batched.bucket_cache.hits") == 1


def test_bucket_ladder():
    assert [bucket_size(n) for n in (1, 2, 3, 5, 16, 17, 256)] == [
        1, 2, 4, 8, 16, 32, 256]
    assert bucket_size(300) == 300          # beyond the ladder: exact size
    assert DEFAULT_BUCKETS[-1] == 256
    with pytest.raises(ValueError):
        bucket_size(0)


def test_explicit_bucket_and_too_small_bucket():
    p = Problem(M=20, N=20)
    bat = solve_batched(p, rhs_gates=(1.0, 2.0), bucket=8)
    assert np.asarray(bat.iterations).shape == (2,)
    with pytest.raises(ValueError, match="bucket 1 smaller than batch"):
        solve_batched(p, rhs_gates=(1.0, 2.0), bucket=1)


def test_mesh_composition_rejects_unwired_families():
    """mesh= composes with the plain multi-RHS forms (PR 12; parity
    pinned in tests/test_placement.py); the executable families without
    a sharded program must still be rejected loudly, never silently
    mis-sharded."""
    import jax

    from poisson_tpu.parallel.mesh import make_solver_mesh

    p = Problem(M=20, N=20)
    mesh = make_solver_mesh(jax.devices()[:1])
    with pytest.raises(ValueError, match="geometries"):
        solve_batched(p, rhs_gates=(1.0,), mesh=mesh,
                      geometries=[{"type": "ellipse"}])
    with pytest.raises(ValueError, match="Jacobi"):
        solve_batched(p, rhs_gates=(1.0,), mesh=mesh,
                      preconditioner="mg")
    with pytest.raises(ValueError, match="integrity probe"):
        solve_batched(p, rhs_gates=(1.0,), mesh=mesh, verify_every=5)


def test_mismatched_problems_rejected():
    with pytest.raises(ValueError, match="share the operator"):
        solve_batched([Problem(M=20, N=20), Problem(M=22, N=20)])


def test_input_form_validation():
    p = Problem(M=20, N=20)
    with pytest.raises(ValueError, match="exactly one of"):
        solve_batched(p)
    with pytest.raises(ValueError, match="exactly one of"):
        solve_batched(p, rhs_gates=(1.0,), rhs_stack=np.zeros((1, 21, 21)))
    with pytest.raises(ValueError, match="at least one"):
        solve_batched([])


def test_max_iter_cap_respected_per_member():
    """A capped batched solve freezes members at the cap exactly like the
    sequential loop (cond: k < max_iter)."""
    p = Problem(M=20, N=20, max_iter=5)
    seq = pcg_solve(p, rhs_gate=1.0)
    bat = solve_batched(p, rhs_gates=(1.0, 1.0))
    assert int(seq.iterations) == 5
    assert [int(k) for k in np.asarray(bat.iterations)] == [5, 5]
    np.testing.assert_array_equal(np.asarray(bat.w)[0], np.asarray(seq.w))


def test_solve_report_handles_member_vector():
    """The report path must format batched results: scalar slots carry the
    fused-loop max, the member vector rides alongside (satellite: vector
    iterations must never crash a report line)."""
    from poisson_tpu.utils.timing import solve_report

    p = Problem(M=20, N=20)
    bat = solve_batched(p, rhs_gates=GATES)
    rep = solve_report(p, bat, solve_seconds=0.1, compile_seconds=0.0,
                       dtype="float64", backend="xla_batched")
    assert rep.iterations == int(bat.max_iterations)
    assert rep.batch == len(GATES)
    assert rep.iterations_per_member == [
        int(k) for k in np.asarray(bat.iterations)]
    assert "members" in rep.table()
    json.loads(rep.json_line())     # serializable


def test_ops_accept_batch_dimension_directly():
    """PCGOps / ops.stencil are batch-polymorphic without vmap: a
    (B, M+1, N+1) stack gets per-member stencil applications and
    per-member reductions identical to the unbatched ops per slice."""
    from poisson_tpu.solvers.pcg import host_setup, single_device_ops

    p = Problem(M=20, N=20)
    a, b, rhs, aux = host_setup(p, "float64", False)
    ops = single_device_ops(p, a, b, aux)
    stack = jnp.stack([rhs, 2.0 * rhs, 0.5 * rhs])

    for name, fn in [("apply_A", ops.apply_A),
                     ("apply_Dinv", ops.apply_Dinv)]:
        out = fn(stack)
        assert out.shape == stack.shape, name
        for i in range(3):
            np.testing.assert_array_equal(np.asarray(out[i]),
                                          np.asarray(fn(stack[i])), name)
    dots = ops.dot(stack, stack)
    sqs = ops.sqnorm(stack)
    assert dots.shape == (3,) and sqs.shape == (3,)
    for i in range(3):
        assert float(dots[i]) == float(ops.dot(stack[i], stack[i]))
        assert float(sqs[i]) == float(ops.sqnorm(stack[i]))


def test_solve_report_flag_aggregation_not_fooled_by_cap_hit():
    """A batch with a budget-exhausted member (FLAG_NONE=0) must not be
    reported as converged just because max(0, 1) == FLAG_CONVERGED; and a
    failure member must surface as the stop verdict."""
    from poisson_tpu.solvers.pcg import (
        FLAG_NONE,
        FLAG_NONFINITE,
        PCGResult,
    )
    from poisson_tpu.utils.timing import solve_report

    p = Problem(M=20, N=20)

    def fake(flags):
        n = len(flags)
        return PCGResult(
            w=np.zeros((n,) + p.grid_shape), iterations=np.array([3] * n),
            diff=np.array([0.5] * n), residual_dot=np.array([1.0] * n),
            flag=np.array(flags, np.int32), max_iterations=np.int32(3))

    metrics.reset()
    rep = solve_report(p, fake([FLAG_NONE, FLAG_CONVERGED]), 0.1, 0.0,
                       dtype="x")
    assert rep.stopped is None                      # cap-hit ≠ failure…
    assert metrics.get("pcg.solves.running") == 1   # …but ≠ converged too
    assert metrics.get("pcg.solves.converged") == 0
    rep = solve_report(p, fake([FLAG_CONVERGED, FLAG_NONFINITE]), 0.1, 0.0,
                       dtype="x")
    assert rep.stopped == "nonfinite"


def test_bucket_executable_shared_across_f_val():
    """f_val never enters the traced program, so batches differing only in
    RHS magnitude must reuse the bucket executable (counter parity with
    the jit cache — the review's counter-vs-jit-key mismatch)."""
    p = Problem(M=20, N=20)
    metrics.reset()
    solve_batched([p, p.with_(f_val=2.0)])
    assert metrics.get("batched.bucket_cache.misses") == 1
    solve_batched([p.with_(f_val=3.0), p.with_(f_val=0.5)])
    assert metrics.get("batched.bucket_cache.hits") == 1
    assert metrics.get("batched.bucket_cache.misses") == 1


def test_iterations_scalar_helper():
    from poisson_tpu.solvers.pcg import iterations_scalar

    assert iterations_scalar(np.int32(7)) == 7
    assert iterations_scalar(np.array([3, 9, 5])) == 9


def test_selfcheck_smoke(capsys):
    from poisson_tpu.solvers.batched_selfcheck import run_selfcheck

    assert run_selfcheck() == 0
    assert "batched selfcheck OK" in capsys.readouterr().out


def test_cli_solve_batched_json(capsys):
    from poisson_tpu.cli import main

    assert main(["solve-batched", "30", "30", "--batch", "3",
                 "--vary-rhs", "--compare-sequential", "--json"]) == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["batch"] == 3
    assert rec["bucket"] == 4
    assert rec["converged"] == 3
    assert len(rec["iterations"]) == 3
    assert rec["max_iterations"] == max(rec["iterations"])
    assert rec["iterations_match_sequential"] is True
    assert rec["solves_per_sec"] > 0


def test_cli_solve_batched_table(capsys):
    from poisson_tpu.cli import main

    assert main(["solve-batched", "30", "30", "--batch", "2"]) == 0
    out = capsys.readouterr().out
    assert "batch=2" in out and "solves/s" in out


def test_compile_cache_counters_wiring(tmp_path, monkeypatch):
    """POISSON_TPU_COMPILE_CACHE enables the persistent cache and the
    monitoring listener folds JAX's cache events into obs counters."""
    import jax

    from poisson_tpu.utils import compile_cache

    saved = (jax.config.jax_compilation_cache_dir,
             jax.config.jax_persistent_cache_min_entry_size_bytes,
             jax.config.jax_persistent_cache_min_compile_time_secs)
    monkeypatch.setenv(compile_cache.ENV_VAR, str(tmp_path / "cc"))
    try:
        assert compile_cache.enable_from_env() is True
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "cc")
        metrics.reset()
        # The listener is wired to the jax.monitoring bus: a cache event
        # on the bus must land in the counters (platform-independent,
        # unlike provoking a real persistent-cache round trip on every
        # backend).
        from jax import monitoring

        monitoring.record_event("/jax/compilation_cache/cache_hits")
        monitoring.record_event("/jax/compilation_cache/cache_misses")
        monitoring.record_event("/jax/unrelated/event")
        assert metrics.get("compile_cache.hits") == 1
        assert metrics.get("compile_cache.misses") == 1
    finally:
        # The cache dir is process-global jax config and tmp_path is
        # about to vanish — restore so later tests never persist into a
        # deleted directory.
        jax.config.update("jax_compilation_cache_dir", saved[0])
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          saved[1])
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          saved[2])


def test_compile_cache_disabled_without_env(monkeypatch):
    from poisson_tpu.utils import compile_cache

    monkeypatch.delenv(compile_cache.ENV_VAR, raising=False)
    assert compile_cache.enable_from_env() is False


def test_bench_batched_record_shape():
    """bench.py --batch on a tiny grid: one JSON line with the throughput
    schema and sequential-parity bit (subprocess: bench owns sys.argv)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--batch", "3", "20", "20"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "batched_solves_per_sec"
    assert rec["unit"] == "solves/sec"
    assert rec["value"] > 0
    assert rec["detail"]["batch"] == 3
    assert rec["detail"]["bucket"] == 4
    assert rec["detail"]["iterations_match_sequential"] is True
    assert rec["detail"]["converged"] == 3
    assert "speedup_vs_sequential" in rec


def test_summarize_session_renders_batched_rows(tmp_path, capsys):
    """The session summarizer shows solves/sec (not a fake MLUPS) for
    batched bench records."""
    import sys

    from benchmarks import summarize_session as ss

    log = tmp_path / "session.jsonl"
    log.write_text(json.dumps({
        "step": "bench_batched", "at": "2026-08-04T00:00:00Z", "ok": True,
        "result": {
            "metric": "batched_solves_per_sec", "value": 123.4,
            "unit": "solves/sec", "speedup_vs_sequential": 3.21,
            "detail": {"grid": [400, 600], "batch": 16, "bucket": 16,
                       "iterations": 546,
                       "iterations_match_sequential": True,
                       "backend": "xla_batched", "platform": "tpu"},
        },
    }) + "\n")
    old = sys.argv
    sys.argv = ["summarize_session.py", str(log)]
    try:
        assert ss.main() == 0
    finally:
        sys.argv = old
    out = capsys.readouterr().out
    assert "123.4 sv/s" in out
    assert "B=16" in out
    assert "3.21x vs seq" in out
