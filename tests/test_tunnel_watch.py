"""Behavioral tests for benchmarks/tunnel_watch.sh.

The watch loop is the mechanism that converts a transient healthy-tunnel
window into committed hardware evidence — a bug in its re-arm/pidfile/
exit logic silently costs the round its only measurement opportunity
(the round-3 postmortem). These tests run the real script with a stubbed
``python`` whose behavior is scripted per-call through control files, so
every decision path executes in seconds with zero TPU contact.

Stub protocol (see ``_stub``): the fake interpreter distinguishes a
probe (``-c`` with the jax snippet) from a session launch
(``benchmarks/tpu_session.py ...``), consumes one line of its control
file per call (``healthy``/``wedged`` for probes, an integer exit code
for sessions), and appends what it saw — including any --outdir /
--resume-after argv — to a call log the assertions read. A session call
additionally consumes one line of ``session_jsonl`` (when that control
file exists) and appends it to the results dir's ``session.jsonl``,
emulating a real session's log growth so the watch's timeout-scan exit
policy can be exercised.
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import time

import pytest

_ROOT = pathlib.Path(__file__).resolve().parents[1]
_SCRIPT = _ROOT / "benchmarks" / "tunnel_watch.sh"

_STUB = r"""#!/bin/bash
# Fake python for tunnel_watch tests. $CTRL_DIR is baked in at write time.
CTRL={ctrl}
LOG=$CTRL/calls.log
if [ "$1" = "-c" ]; then
    echo "probe" >> "$LOG"
    verdict=$(head -n1 "$CTRL/probes")
    sed -i 1d "$CTRL/probes"
    [ "$verdict" = "healthy" ] && exit 0
    exit 1
fi
echo "session $*" >> "$LOG"
if [ -f "$CTRL/session_jsonl" ]; then
    line=$(head -n1 "$CTRL/session_jsonl")
    sed -i 1d "$CTRL/session_jsonl"
    [ -n "$line" ] && echo "$line" >> "$TUNNEL_WATCH_RESULTS/session.jsonl"
fi
rc=$(head -n1 "$CTRL/sessions")
sed -i 1d "$CTRL/sessions"
exit "$rc"
"""


class Harness:
    def __init__(self, tmp_path: pathlib.Path):
        self.ctrl = tmp_path / "ctrl"
        self.results = tmp_path / "results"
        self.repo = tmp_path / "repo"
        for d in (self.ctrl, self.results, self.repo):
            d.mkdir()
        stub = tmp_path / "fakepython"
        stub.write_text(_STUB.format(ctrl=self.ctrl))
        stub.chmod(0o755)
        self.stub = stub
        (self.ctrl / "calls.log").write_text("")
        # What every session launch line looks like (the watch aligns the
        # session's outdir with its own results dir — the timeout-scan
        # reads the session.jsonl the session actually writes).
        self.session_call = f"session benchmarks/tpu_session.py " \
                            f"--outdir {self.results}"
        self.env = {
            **os.environ,
            "TUNNEL_WATCH_REPO": str(self.repo),
            "TUNNEL_WATCH_RESULTS": str(self.results),
            "TUNNEL_WATCH_PYTHON": str(stub),
            "TUNNEL_WATCH_POLL": "0",
            "TUNNEL_WATCH_COOLDOWN": "0",
            "TUNNEL_WATCH_PROBE_TIMEOUT": "5",
        }

    def script(self, probes: list[str], sessions: list[int],
               session_jsonl: list[str] | None = None):
        (self.ctrl / "probes").write_text(
            "".join(p + "\n" for p in probes)
        )
        (self.ctrl / "sessions").write_text(
            "".join(f"{rc}\n" for rc in sessions)
        )
        if session_jsonl is not None:
            (self.ctrl / "session_jsonl").write_text(
                "".join(line + "\n" for line in session_jsonl)
            )

    def run(self, timeout=20) -> subprocess.CompletedProcess:
        return subprocess.run(
            ["bash", str(_SCRIPT)], env=self.env, text=True,
            capture_output=True, timeout=timeout,
        )

    def calls(self) -> list[str]:
        return (self.ctrl / "calls.log").read_text().splitlines()

    def log(self) -> str:
        return (self.results / "tunnel_probe.log").read_text()


@pytest.fixture()
def harness(tmp_path):
    return Harness(tmp_path)


def test_clean_session_exits_watch(harness):
    harness.script(probes=["wedged", "healthy"], sessions=[0])
    proc = harness.run()
    assert proc.returncode == 0
    calls = harness.calls()
    # one failed probe, one healthy probe, one session, then exit —
    # crucially NO further probes after the clean session (the watch must
    # stop being a tunnel client).
    assert calls == ["probe", "probe", harness.session_call]
    assert "watch done (clean session)" in harness.log()
    # pidfile cleaned up on exit; done sentinel written
    assert not (harness.results / "tunnel_watch.pid").exists()
    assert (harness.results / "watch_done").exists()


def test_done_sentinel_idles_restarted_watch(harness):
    # A restarted watch after a finished one must NOT re-run the whole
    # multi-hour session (review finding on the marker-reclaim fix): the
    # watch_done sentinel makes it exit before any tunnel contact.
    (harness.results / "watch_done").write_text("2026-07-30T12:00:00Z\n")
    harness.script(probes=["healthy"], sessions=[0])
    proc = harness.run()
    assert proc.returncode == 0
    assert harness.calls() == []
    assert "evidence already captured" in harness.log()


def test_failed_session_rearms_with_resume(harness):
    harness.script(probes=["healthy", "healthy"], sessions=[2, 0])
    proc = harness.run()
    assert proc.returncode == 0
    calls = harness.calls()
    assert calls[0] == "probe"
    assert calls[1] == harness.session_call
    # the re-armed launch passes --resume-after <watch start>
    assert calls[2] == "probe"
    assert calls[3].startswith(harness.session_call + " --resume-after ")
    assert "watch done (clean session)" in harness.log()


def test_identity_gate_failure_rearms_too(harness):
    # rc=1 (tunnel died between probe and identity step) re-arms exactly
    # like the wedge-defense rc=2.
    harness.script(probes=["healthy", "healthy"], sessions=[1, 0])
    proc = harness.run()
    assert proc.returncode == 0
    assert [c.split()[0] for c in harness.calls()] == [
        "probe", "session", "probe", "session"
    ]


def test_wedged_probes_never_launch(harness):
    # All probes wedged: loop keeps probing; kill it after a few polls
    # and verify no session was ever attempted. A small nonzero poll
    # keeps the loop from busy-forking, and the timeout is generous so a
    # loaded machine still completes several probes first.
    harness.env["TUNNEL_WATCH_POLL"] = "0.1"
    harness.script(probes=["wedged"] * 500, sessions=[])
    with pytest.raises(subprocess.TimeoutExpired):
        harness.run(timeout=8)
    calls = harness.calls()
    assert calls and all(c == "probe" for c in calls)
    assert "wedged" in harness.log()


def test_second_instance_bows_out(harness):
    # A live pid in the pidfile (this test process) must make a new watch
    # exit immediately without probing.
    (harness.results / "tunnel_watch.pid").write_text(str(os.getpid()))
    harness.script(probes=["healthy"], sessions=[0])
    proc = harness.run()
    assert proc.returncode == 0
    assert harness.calls() == []
    assert "is alive; exiting" in harness.log()
    # the live owner's pidfile is left untouched
    assert (harness.results / "tunnel_watch.pid").read_text() == str(
        os.getpid()
    )


def test_stale_marker_is_cleared_at_startup(harness):
    # A session_launched marker whose recorded session PID is dead (or
    # that is empty — the pre-PID format) must not stop a new watch from
    # launching (round-4 advisor finding: the marker persisted forever).
    (harness.results / "session_launched").touch()
    harness.script(probes=["healthy"], sessions=[0])
    proc = harness.run()
    assert proc.returncode == 0
    assert harness.calls() == ["probe", harness.session_call]
    assert "watch done (clean session)" in harness.log()


def test_live_orphan_session_stands_watch_down(harness):
    # A marker holding a live pid whose cmdline IS a session process
    # means a killed watch's session is still running: the new watch
    # must not probe (probes are TPU clients) and must not launch a
    # second session (review finding on the blind-removal version of
    # the stale-marker fix).
    orphan = subprocess.Popen(
        ["bash", "-c", "exec -a fake-tpu_session.py sleep 60"]
    )
    try:
        (harness.results / "session_launched").write_text(str(orphan.pid))
        harness.env["TUNNEL_WATCH_POLL"] = "0.1"
        harness.script(probes=["healthy"] * 50, sessions=[0])
        with pytest.raises(subprocess.TimeoutExpired):
            harness.run(timeout=5)
        assert harness.calls() == []
        assert "standing down" in harness.log()
    finally:
        orphan.kill()
        orphan.wait()


def test_reused_pid_does_not_park_the_watch(harness):
    # kill -0 alone is not identity: a live pid whose cmdline is NOT a
    # session process (PID reuse after reboot) must be reclaimed, not
    # stood down behind forever (review finding).
    bystander = subprocess.Popen(["sleep", "60"])
    try:
        (harness.results / "session_launched").write_text(
            str(bystander.pid)
        )
        harness.script(probes=["healthy"], sessions=[0])
        proc = harness.run()
        assert proc.returncode == 0
        assert harness.calls() == ["probe", harness.session_call]
        assert "standing down" not in harness.log()
    finally:
        bystander.kill()
        bystander.wait()


_TIMEOUT_LINE = (
    '{"step": "bench_2400x3200", "at": "2026-07-30T12:00:00+00:00", '
    '"ok": false, "error": "timeout>1800s"}'
)
_OK_LINE = (
    '{"step": "bench_2400x3200", "at": "2026-07-30T13:00:00+00:00", '
    '"ok": true, "result": {"value": 1.0}}'
)


def test_clean_session_with_timeouts_stays_armed(harness):
    # A clean (rc=0) session whose run recorded a step timeout must NOT
    # end the watch: a later, longer window should top up the missing
    # step (round-4 judge item). The second, timeout-free clean session
    # ends it.
    harness.script(probes=["healthy", "healthy"], sessions=[0, 0],
                   session_jsonl=[_TIMEOUT_LINE, _OK_LINE])
    proc = harness.run()
    assert proc.returncode == 0
    calls = harness.calls()
    assert calls[0] == "probe"
    assert calls[1] == harness.session_call
    assert calls[2] == "probe"
    # the top-up relaunch replays this generation's completed steps
    assert calls[3].startswith(harness.session_call + " --resume-after ")
    assert "staying armed (top-up 1/" in harness.log()
    assert "watch done (clean session)" in harness.log()


def test_topup_cap_bounds_persistent_timeouts(harness):
    # A step that times out in EVERY window must not pin the tunnel
    # forever: after MAX_TOPUPS relaunches the watch exits clean (and
    # writes the done sentinel — the evidence that exists is captured).
    harness.env["TUNNEL_WATCH_MAX_TOPUPS"] = "1"
    harness.script(probes=["healthy", "healthy"], sessions=[0, 0],
                   session_jsonl=[_TIMEOUT_LINE, _TIMEOUT_LINE])
    proc = harness.run()
    assert proc.returncode == 0
    assert [c.split()[0] for c in harness.calls()] == [
        "probe", "session", "probe", "session"
    ]
    assert "persist after 1 top-up(s)" in harness.log()
    assert (harness.results / "watch_done").exists()


def test_stale_pidfile_is_reclaimed(harness):
    # A dead owner's pidfile must not block a new watch.
    dead = subprocess.Popen(["true"])
    dead.wait()
    (harness.results / "tunnel_watch.pid").write_text(str(dead.pid))
    # give the pid a moment to be certainly unkillable-0
    time.sleep(0.1)
    harness.script(probes=["healthy"], sessions=[0])
    proc = harness.run()
    assert proc.returncode == 0
    assert "watch done (clean session)" in harness.log()
