"""Behavioral tests for benchmarks/tunnel_watch.sh.

The watch loop is the mechanism that converts a transient healthy-tunnel
window into committed hardware evidence — a bug in its re-arm/pidfile/
exit logic silently costs the round its only measurement opportunity
(the round-3 postmortem). These tests run the real script with a stubbed
``python`` whose behavior is scripted per-call through control files, so
every decision path executes in seconds with zero TPU contact.

Stub protocol (see ``_stub``): the fake interpreter distinguishes a
probe (``-c`` with the jax snippet) from a session launch
(``benchmarks/tpu_session.py ...``), consumes one line of its control
file per call (``healthy``/``wedged`` for probes, an integer exit code
for sessions), and appends what it saw — including any --resume-after
argv — to a call log the assertions read.
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import time

import pytest

_ROOT = pathlib.Path(__file__).resolve().parents[1]
_SCRIPT = _ROOT / "benchmarks" / "tunnel_watch.sh"

_STUB = r"""#!/bin/bash
# Fake python for tunnel_watch tests. $CTRL_DIR is baked in at write time.
CTRL={ctrl}
LOG=$CTRL/calls.log
if [ "$1" = "-c" ]; then
    echo "probe" >> "$LOG"
    verdict=$(head -n1 "$CTRL/probes")
    sed -i 1d "$CTRL/probes"
    [ "$verdict" = "healthy" ] && exit 0
    exit 1
fi
echo "session $*" >> "$LOG"
rc=$(head -n1 "$CTRL/sessions")
sed -i 1d "$CTRL/sessions"
exit "$rc"
"""


class Harness:
    def __init__(self, tmp_path: pathlib.Path):
        self.ctrl = tmp_path / "ctrl"
        self.results = tmp_path / "results"
        self.repo = tmp_path / "repo"
        for d in (self.ctrl, self.results, self.repo):
            d.mkdir()
        stub = tmp_path / "fakepython"
        stub.write_text(_STUB.format(ctrl=self.ctrl))
        stub.chmod(0o755)
        self.stub = stub
        (self.ctrl / "calls.log").write_text("")
        self.env = {
            **os.environ,
            "TUNNEL_WATCH_REPO": str(self.repo),
            "TUNNEL_WATCH_RESULTS": str(self.results),
            "TUNNEL_WATCH_PYTHON": str(stub),
            "TUNNEL_WATCH_POLL": "0",
            "TUNNEL_WATCH_COOLDOWN": "0",
            "TUNNEL_WATCH_PROBE_TIMEOUT": "5",
        }

    def script(self, probes: list[str], sessions: list[int]):
        (self.ctrl / "probes").write_text(
            "".join(p + "\n" for p in probes)
        )
        (self.ctrl / "sessions").write_text(
            "".join(f"{rc}\n" for rc in sessions)
        )

    def run(self, timeout=20) -> subprocess.CompletedProcess:
        return subprocess.run(
            ["bash", str(_SCRIPT)], env=self.env, text=True,
            capture_output=True, timeout=timeout,
        )

    def calls(self) -> list[str]:
        return (self.ctrl / "calls.log").read_text().splitlines()

    def log(self) -> str:
        return (self.results / "tunnel_probe.log").read_text()


@pytest.fixture()
def harness(tmp_path):
    return Harness(tmp_path)


def test_clean_session_exits_watch(harness):
    harness.script(probes=["wedged", "healthy"], sessions=[0])
    proc = harness.run()
    assert proc.returncode == 0
    calls = harness.calls()
    # one failed probe, one healthy probe, one session, then exit —
    # crucially NO further probes after the clean session (the watch must
    # stop being a tunnel client).
    assert calls == ["probe", "probe", "session benchmarks/tpu_session.py"]
    assert "watch done (clean session)" in harness.log()
    # pidfile cleaned up on exit
    assert not (harness.results / "tunnel_watch.pid").exists()


def test_failed_session_rearms_with_resume(harness):
    harness.script(probes=["healthy", "healthy"], sessions=[2, 0])
    proc = harness.run()
    assert proc.returncode == 0
    calls = harness.calls()
    assert calls[0] == "probe"
    assert calls[1] == "session benchmarks/tpu_session.py"
    # the re-armed launch passes --resume-after <watch start>
    assert calls[2] == "probe"
    assert calls[3].startswith(
        "session benchmarks/tpu_session.py --resume-after "
    )
    assert "watch done (clean session)" in harness.log()


def test_identity_gate_failure_rearms_too(harness):
    # rc=1 (tunnel died between probe and identity step) re-arms exactly
    # like the wedge-defense rc=2.
    harness.script(probes=["healthy", "healthy"], sessions=[1, 0])
    proc = harness.run()
    assert proc.returncode == 0
    assert [c.split()[0] for c in harness.calls()] == [
        "probe", "session", "probe", "session"
    ]


def test_wedged_probes_never_launch(harness):
    # All probes wedged: loop keeps probing; kill it after a few polls
    # and verify no session was ever attempted. A small nonzero poll
    # keeps the loop from busy-forking, and the timeout is generous so a
    # loaded machine still completes several probes first.
    harness.env["TUNNEL_WATCH_POLL"] = "0.1"
    harness.script(probes=["wedged"] * 500, sessions=[])
    with pytest.raises(subprocess.TimeoutExpired):
        harness.run(timeout=8)
    calls = harness.calls()
    assert calls and all(c == "probe" for c in calls)
    assert "wedged" in harness.log()


def test_second_instance_bows_out(harness):
    # A live pid in the pidfile (this test process) must make a new watch
    # exit immediately without probing.
    (harness.results / "tunnel_watch.pid").write_text(str(os.getpid()))
    harness.script(probes=["healthy"], sessions=[0])
    proc = harness.run()
    assert proc.returncode == 0
    assert harness.calls() == []
    assert "is alive; exiting" in harness.log()
    # the live owner's pidfile is left untouched
    assert (harness.results / "tunnel_watch.pid").read_text() == str(
        os.getpid()
    )


def test_stale_pidfile_is_reclaimed(harness):
    # A dead owner's pidfile must not block a new watch.
    dead = subprocess.Popen(["true"])
    dead.wait()
    (harness.results / "tunnel_watch.pid").write_text(str(dead.pid))
    # give the pid a moment to be certainly unkillable-0
    time.sleep(0.1)
    harness.script(probes=["healthy"], sessions=[0])
    proc = harness.run()
    assert proc.returncode == 0
    assert "watch done (clean session)" in harness.log()
