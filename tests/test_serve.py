"""Solve-service lifecycle: admission, deadlines, retry/backoff, circuit
breaking, graceful degradation (tier-1, CPU-deterministic; -m serve).

Every test drives the real solver stack on tiny grids through the
single-threaded service loop with an injected virtual clock — no
wall-clock sleeps, no thread races: timing-dependent behaviour
(deadlines, backoff, breaker cooldowns) is a pure function of the
injected clock and the campaign seed.
"""

import json

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.obs import metrics
from poisson_tpu.serve import (
    CLOSED,
    Deadline,
    HALF_OPEN,
    OPEN,
    BreakerPolicy,
    CircuitBreaker,
    DegradationPolicy,
    OUTCOME_ERROR,
    OUTCOME_RESULT,
    OUTCOME_SHED,
    RetryPolicy,
    ServicePolicy,
    SolveRequest,
    SolveService,
    TransientDispatchError,
)
from poisson_tpu.testing.chaos import VirtualClock

pytestmark = pytest.mark.serve

P40 = Problem(M=40, N=40)          # converges in 50 iterations (golden)


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    yield
    metrics.reset()


def _service(policy=None, **kw):
    vc = VirtualClock()
    svc = SolveService(policy or ServicePolicy(), clock=vc,
                       sleep=vc.sleep, **kw)
    return svc, vc


def _quiet_degradation():
    return DegradationPolicy(shrink_padding_at=9.0, cap_iterations_at=9.0,
                             downshift_precision_at=9.0)


# -- typed outcomes & the ledger ---------------------------------------


def test_every_request_gets_exactly_one_typed_outcome():
    svc, _ = _service()
    for i in range(5):
        assert svc.submit(SolveRequest(request_id=i, problem=P40,
                                       rhs_gate=1.0 + i / 10)) is None
    outs = svc.drain()
    assert sorted(o.request_id for o in outs) == list(range(5))
    assert all(o.kind == OUTCOME_RESULT and o.converged for o in outs)
    stats = svc.stats()
    assert stats["lost"] == 0 and stats["pending"] == 0
    assert metrics.get("serve.admitted") == 5
    assert metrics.get("serve.completed") == 5


def test_duplicate_request_id_rejected():
    svc, _ = _service()
    svc.submit(SolveRequest(request_id="a", problem=P40))
    with pytest.raises(ValueError, match="duplicate request_id"):
        svc.submit(SolveRequest(request_id="a", problem=P40))


def test_bounded_admission_sheds_typed():
    svc, _ = _service(ServicePolicy(capacity=2))
    assert svc.submit(SolveRequest(request_id=0, problem=P40)) is None
    assert svc.submit(SolveRequest(request_id=1, problem=P40)) is None
    shed = svc.submit(SolveRequest(request_id=2, problem=P40))
    assert shed is not None and shed.kind == OUTCOME_SHED
    assert shed.shed_reason == "queue_full"
    svc.drain()
    s = svc.stats()
    # The shed request is in the ledger: admitted and terminated.
    assert s["admitted"] == 3 and s["shed"] == 1 and s["lost"] == 0


# -- circuit breaker ----------------------------------------------------


def test_breaker_trip_half_open_close_transitions():
    vc = VirtualClock()
    br = CircuitBreaker(BreakerPolicy(failure_threshold=3,
                                      cooldown_seconds=10.0,
                                      half_open_probes=1),
                        clock=vc, cohort="t")
    assert br.state == CLOSED and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED          # below threshold
    br.record_failure()
    assert br.state == OPEN and not br.allow()
    assert metrics.get("serve.breaker.trips") == 1
    vc.advance(9.9)
    assert not br.allow()              # still cooling down
    vc.advance(0.2)
    assert br.state == HALF_OPEN
    assert br.allow()                  # the probe slot
    assert not br.allow()              # only one probe
    br.record_success()
    assert br.state == CLOSED and br.allow()
    assert metrics.get("serve.breaker.half_opens") == 1
    assert metrics.get("serve.breaker.closes") == 1


def test_breaker_reopens_on_failed_probe():
    vc = VirtualClock()
    br = CircuitBreaker(BreakerPolicy(failure_threshold=2,
                                      cooldown_seconds=5.0),
                        clock=vc, cohort="t")
    br.record_failure()
    br.record_failure()
    vc.advance(5.1)
    assert br.allow()                  # probe
    br.record_failure()                # probe failed
    assert br.state == OPEN
    assert metrics.get("serve.breaker.trips") == 2


def test_success_resets_consecutive_failure_count():
    br = CircuitBreaker(BreakerPolicy(failure_threshold=2), cohort="t")
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == CLOSED          # never two consecutive


def test_open_breaker_sheds_requests_typed():
    fail = {"on": True}

    def fault(requests, attempts):
        if fail["on"]:
            raise TransientDispatchError("outage")

    svc, vc = _service(
        ServicePolicy(retry=RetryPolicy(max_attempts=1),
                      breaker=BreakerPolicy(failure_threshold=2,
                                            cooldown_seconds=3.0),
                      degradation=_quiet_degradation()),
        dispatch_fault=fault,
    )
    for i in range(2):
        svc.submit(SolveRequest(request_id=i, problem=P40))
        svc.drain()                    # two consecutive typed errors
    svc.submit(SolveRequest(request_id=2, problem=P40))
    (shed,) = svc.drain()
    assert shed.kind == OUTCOME_SHED and shed.shed_reason == "breaker_open"
    fail["on"] = False
    vc.advance(3.1)
    svc.submit(SolveRequest(request_id=3, problem=P40))
    (probe,) = svc.drain()
    assert probe.converged
    assert svc.stats()["breakers"]["40x40:auto:xla"] == CLOSED


# -- deadlines ----------------------------------------------------------


def test_deadline_object_semantics():
    vc = VirtualClock()
    d = Deadline(2.0, clock=vc)
    assert not d.expired() and d.remaining() == pytest.approx(2.0)
    vc.advance(2.5)
    assert d.expired() and d.remaining() == pytest.approx(-0.5)
    assert not Deadline.never().expired()
    assert Deadline.never().remaining() is None
    with pytest.raises(ValueError):
        Deadline(-1.0)


def test_deadline_expiry_mid_chunk_returns_partial_flagged_result():
    from poisson_tpu.solvers.checkpoint import pcg_solve_chunked
    from poisson_tpu.solvers.pcg import FLAG_DEADLINE

    vc = VirtualClock()

    def tick(state, chunks_done):
        vc.advance(0.4)
        return None

    res = pcg_solve_chunked(P40, chunk=5, deadline=Deadline(1.0, clock=vc),
                            on_chunk=tick)
    assert int(res.flag) == FLAG_DEADLINE
    assert 0 < int(res.iterations) < 50        # partial, not a hang
    assert bool(np.isfinite(np.asarray(res.w)).all())
    assert metrics.get("checkpoint.deadline_stops") == 1


def test_deadline_never_masks_a_failure_verdict():
    """A solve that DIVERGED keeps its honest verdict even when the
    deadline has also lapsed during the failing chunk: stamping
    FLAG_DEADLINE over nonfinite would hand the poisoned iterate out as
    a usable partial result and skip the service's retry/escalation
    path. A NaN RHS dies inside chunk 1; the ticking clock makes the
    deadline expire across that same chunk."""
    from poisson_tpu.solvers.checkpoint import pcg_solve_chunked
    from poisson_tpu.solvers.pcg import FLAG_NONFINITE

    t = {"now": 0.0}

    def ticking_clock():               # every observation costs 0.6 s
        t["now"] += 0.6
        return t["now"]

    res = pcg_solve_chunked(P40, chunk=5, rhs_gate=float("nan"),
                            deadline=Deadline(1.0, clock=ticking_clock))
    assert int(res.flag) == FLAG_NONFINITE


def test_deadline_never_overrides_convergence():
    from poisson_tpu.solvers.checkpoint import pcg_solve_chunked
    from poisson_tpu.solvers.pcg import FLAG_CONVERGED

    vc = VirtualClock()
    # Expires only after the solve would already have converged.
    res = pcg_solve_chunked(P40, chunk=100,
                            deadline=Deadline(1e9, clock=vc))
    assert int(res.flag) == FLAG_CONVERGED
    assert int(res.iterations) == 50


def test_deadline_stopped_checkpoint_resumes_clean(tmp_path):
    """FLAG_DEADLINE is host-stamped provenance on the RESULT only: the
    persisted state keeps its in-loop verdict, so a rerun with a fresh
    budget resumes from the partial iterate and converges to the golden
    sequence."""
    from poisson_tpu.solvers.checkpoint import (
        pcg_solve_checkpointed,
        pcg_solve_chunked,
    )
    from poisson_tpu.solvers.pcg import FLAG_CONVERGED, FLAG_DEADLINE

    path = str(tmp_path / "ck.npz")
    vc = VirtualClock()

    def tick(state, chunks_done):
        vc.advance(1.0)
        return None

    partial = pcg_solve_checkpointed(P40, path, chunk=10,
                                     deadline=Deadline(1.5, clock=vc),
                                     on_chunk=tick)
    assert int(partial.flag) == FLAG_DEADLINE
    assert 0 < int(partial.iterations) < 50
    resumed = pcg_solve_checkpointed(P40, path, chunk=10)
    assert int(resumed.flag) == FLAG_CONVERGED
    golden = pcg_solve_chunked(P40, chunk=10)
    assert int(resumed.iterations) == int(golden.iterations) == 50
    np.testing.assert_array_equal(np.asarray(resumed.w),
                                  np.asarray(golden.w))


def test_resilient_deadline_bounds_recovery():
    from poisson_tpu.solvers.pcg import FLAG_DEADLINE
    from poisson_tpu.solvers.resilient import pcg_solve_resilient

    vc = VirtualClock()
    vc.advance(0.0)
    res = pcg_solve_resilient(P40, chunk=10,
                              deadline=Deadline(0.0, clock=vc))
    assert int(res.flag) == FLAG_DEADLINE
    assert int(res.iterations) == 0            # refused to start a chunk
    assert metrics.get("resilient.deadline_stops") == 1


def test_deadline_vs_watchdog_interaction():
    """The two guards answer different questions and must not cross:
    a mid-chunk STALL trips the watchdog (liveness) while a generous
    deadline stays quiet; and a deadline stop beats like a healthy solve
    (the watchdog must NOT fire on a deadline-bounded run)."""
    import time as _time

    from poisson_tpu.parallel.watchdog import Watchdog
    from poisson_tpu.solvers.checkpoint import pcg_solve_chunked
    from poisson_tpu.solvers.pcg import FLAG_CONVERGED, FLAG_DEADLINE

    # Stall → watchdog fires, deadline quiet.
    fired = []
    wd = Watchdog(timeout=0.15, poll_interval=0.03,
                  on_timeout=fired.append)
    stalled = {"done": False}

    def stall_once(state, chunks_done):
        if not stalled["done"]:
            stalled["done"] = True
            _time.sleep(0.4)
        return None

    res = pcg_solve_chunked(P40, chunk=10, watchdog=wd,
                            on_chunk=stall_once, deadline=Deadline(3600.0))
    assert wd.fired and len(fired) == 1
    assert int(res.flag) == FLAG_CONVERGED     # stall ≠ budget overrun

    # Deadline stop → watchdog quiet (beats kept landing at boundaries).
    vc = VirtualClock()

    def tick(state, chunks_done):
        vc.advance(1.0)
        return None

    wd2 = Watchdog(timeout=30.0, poll_interval=0.05,
                   on_timeout=lambda d: pytest.fail("watchdog misfired"))
    res2 = pcg_solve_chunked(P40, chunk=10, watchdog=wd2,
                             deadline=Deadline(1.5, clock=vc),
                             on_chunk=tick)
    assert int(res2.flag) == FLAG_DEADLINE
    assert not wd2.fired


def test_service_sheds_requests_whose_deadline_died_in_queue():
    from poisson_tpu.testing.faults import slow_worker_fault

    vc = VirtualClock()
    svc = SolveService(
        ServicePolicy(degradation=_quiet_degradation()),
        clock=vc, sleep=vc.sleep,
        dispatch_fault=None,
    )
    # Manually burn the clock between submits via a slow dispatch.
    svc._dispatch_fault = slow_worker_fault(2.0, vc.sleep)
    for i in range(3):
        svc.submit(SolveRequest(request_id=i, problem=P40,
                                deadline_seconds=1.0))
    outs = {o.request_id: o for o in svc.drain()}
    assert outs[0].kind == OUTCOME_RESULT      # dispatched at t=0
    assert outs[1].kind == OUTCOME_SHED        # t=2.0 > deadline
    assert outs[2].kind == OUTCOME_SHED
    assert metrics.get("serve.shed.deadline_expired") == 2


# -- retry / backoff / requeue isolation --------------------------------


def test_backoff_is_seeded_exponential_with_jitter():
    policy = ServicePolicy(retry=RetryPolicy(max_attempts=9,
                                             backoff_base=0.1,
                                             backoff_cap=1.0, jitter=0.5))
    a = SolveService(policy, seed=7)
    b = SolveService(policy, seed=7)
    c = SolveService(policy, seed=8)
    da = [a._backoff_delay(n) for n in range(1, 6)]
    db = [b._backoff_delay(n) for n in range(1, 6)]
    dc = [c._backoff_delay(n) for n in range(1, 6)]
    assert da == db                    # same seed → same jitter
    assert da != dc                    # different seed → different jitter
    for n, d in enumerate(da, start=1):
        base = min(0.1 * 2 ** (n - 1), 1.0)
        assert base * 0.5 <= d <= base # jittered down, capped


def test_poison_member_is_isolated_on_requeue():
    from poisson_tpu.testing.faults import poison_batch_fault

    svc, _ = _service(
        ServicePolicy(retry=RetryPolicy(max_attempts=3,
                                        backoff_base=0.01,
                                        backoff_cap=0.05),
                      degradation=_quiet_degradation()),
        dispatch_fault=poison_batch_fault({"poison"}),
    )
    svc.submit(SolveRequest(request_id="poison", problem=P40))
    for i in range(3):
        svc.submit(SolveRequest(request_id=i, problem=P40))
    outs = {o.request_id: o for o in svc.drain()}
    assert outs["poison"].kind == OUTCOME_ERROR
    assert outs["poison"].error_type == "transient"
    assert outs["poison"].attempts == 3
    assert all(outs[i].converged for i in range(3))
    assert metrics.get("serve.requeued.isolated") >= 3
    assert svc.stats()["lost"] == 0


def test_internal_errors_are_typed_and_never_retried():
    def fault(requests, attempts):
        raise RuntimeError("unexpected bug")

    svc, _ = _service(dispatch_fault=fault)
    svc.submit(SolveRequest(request_id=0, problem=P40))
    (out,) = svc.drain()
    assert out.kind == OUTCOME_ERROR and out.error_type == "internal"
    assert out.attempts == 1
    assert metrics.get("serve.retries") == 0


# -- graceful degradation ----------------------------------------------


def test_degradation_ladder_engages_and_is_audible():
    svc, _ = _service(ServicePolicy(
        capacity=12, max_batch=4,
        degradation=DegradationPolicy(shrink_padding_at=0.5,
                                      cap_iterations_at=0.75,
                                      degraded_iteration_cap=10,
                                      downshift_precision_at=0.9)))
    for i in range(11):
        svc.submit(SolveRequest(request_id=i, problem=P40))
    outs = svc.drain()
    partial = [o for o in outs if o.partial]
    # Peak load: one level-3 batch of 4 → capped at 10 iterations.
    assert len(partial) == 4
    assert all(o.flag == "cap_hit" and o.iterations == 10
               for o in partial)
    assert [o.converged for o in outs].count(True) == 7
    assert metrics.get("serve.degraded.padding") >= 2
    assert metrics.get("serve.degraded.iteration_cap") >= 1
    assert metrics.get("serve.degraded.precision") >= 1
    assert svc.stats()["lost"] == 0


# -- batched origin identity (requeue seam) -----------------------------


def test_solve_batched_origin_rides_through_padding():
    from poisson_tpu.solvers.batched import solve_batched

    res = solve_batched(P40, rhs_gates=[1.0, 1.1, 1.2],
                        member_ids=("r-a", "r-b", "r-c"))
    assert res.origin == ("r-a", "r-b", "r-c")
    assert res.w.shape[0] == 3                 # padding sliced off
    # Default identity mapping.
    assert solve_batched(P40, rhs_gates=[1.0, 1.0]).origin == (0, 1)
    with pytest.raises(ValueError, match="one id per member"):
        solve_batched(P40, rhs_gates=[1.0, 1.0], member_ids=("only",))


# -- exposition ---------------------------------------------------------


def test_latency_percentiles_export_as_prometheus_summary():
    from poisson_tpu.obs import export

    svc, _ = _service()
    svc.submit(SolveRequest(request_id=0, problem=P40))
    svc.drain()
    text = export.render()
    parsed = export.parse_text(text)
    for q in ("0.5", "0.95", "0.99"):
        key = f'poisson_tpu_serve_latency_seconds{{quantile="{q}"}}'
        assert key in parsed, text
        assert parsed[key]["type"] == "summary"
    assert parsed["poisson_tpu_serve_admitted"]["value"] == 1


# -- CLI ----------------------------------------------------------------


def test_serve_cli_json(capsys):
    from poisson_tpu.cli import main

    assert main(["serve", "40", "40", "--requests", "6", "--vary-rhs",
                 "--json"]) == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["completed"] == 6 and rec["lost"] == 0
    assert set(rec["latency_seconds"]) == {"p50", "p95", "p99"}


def test_serve_cli_fault_drill_table(capsys):
    from poisson_tpu.cli import main

    assert main(["serve", "40", "40", "--requests", "6",
                 "--fault-poison", "1"]) == 0
    out = capsys.readouterr().out
    assert "typed errors" in out and "taxonomy:" in out
    assert "error:transient=1" in out
