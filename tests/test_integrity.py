"""Numerical integrity: the silent-data-corruption defense (tier-1,
CPU; -m integrity).

The load-bearing claims, each asserted here:

- **Detection**: every exponent-class bit flip the seeded injector
  lands in {w, r, p, Ap} — across precisions, injection iterations and
  seeds — is detected within ``verify_every`` iterations and the solve
  still converges via a verified restart (never a precision
  escalation). The one carve-out is physics, not tuning: an EARLY
  f32 search-direction flip keeps the recurrence consistent and lands
  inside CG's own step-to-step dynamic range — that regime is pinned
  by the bounded-harm test instead (correct answer, merely slower).
- **Zero false alarms**: clean golden solves (f32 + f64, reference and
  geometry domains) run verified with their golden iteration counts
  and no integrity verdict.
- **Off means off**: ``verify_every=0`` lowers to the byte-identical
  HLO of the pre-integrity program (verbatim-copy pin) and golden
  iteration counts stay bit-for-bit.
- **Per-member masking**: a flip in one lane of a running bucket stops
  only that lane with FLAG_INTEGRITY; co-residents converge untouched.
- **Chaos invariants**: the three SDC scenarios keep the ledger
  invariant admitted − (completed + errors + shed) == 0.
- **Sentinel pins**: ``detail.verify_every`` is experiment identity —
  a verified run never indicts an unverified baseline.
"""

from __future__ import annotations

import types
import warnings

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.integrity import probe
from poisson_tpu.obs import metrics
from poisson_tpu.solvers.pcg import (
    FLAG_CONVERGED,
    FLAG_INTEGRITY,
    host_setup,
    init_state,
    make_pcg_body,
    pcg_solve,
    resolve_scaled,
    single_device_ops,
)
from poisson_tpu.solvers.resilient import pcg_solve_resilient
from poisson_tpu.testing import faults

pytestmark = pytest.mark.integrity

PROBLEM = Problem(M=48, N=72)


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    yield
    metrics.reset()


def _f64_ops(problem=PROBLEM):
    a, b, rhs, aux = host_setup(problem, "float64", False)
    return single_device_ops(problem, a, b, aux), rhs


def _run(ops, rhs, n):
    body = make_pcg_body(ops, delta=PROBLEM.delta,
                         weighted_norm=PROBLEM.weighted_norm,
                         h1=PROBLEM.h1, h2=PROBLEM.h2)
    s = init_state(ops, rhs)
    for _ in range(n):
        s = body(s)
    return s


# -- the injector (testing/faults) --------------------------------------


@pytest.mark.parametrize("value", [1.0, -3.7e-5, 2.2e-11, 0.125])
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_bitflip_element_exponent_is_silent(value, dtype):
    """The exponent-class flip is finite, different from the input, and
    survives squaring with grid-sized headroom — NOTHING loud happens,
    which is the whole point of the fault model (a NaN/Inf is the PR 1
    divergence detector's case, not this layer's)."""
    v = dtype(value)
    flipped = faults.bitflip_element(v, bit_class="exponent")
    assert np.isfinite(flipped) and flipped != v
    assert np.isfinite(np.asarray(flipped, np.float64) ** 2
                       * np.float64(1e6))


def test_bitflip_element_mantissa_and_explicit_bit():
    v = np.float32(1.5)
    m = faults.bitflip_element(v, bit_class="mantissa")
    assert np.isfinite(m) and m != v
    # Mantissa-MSB flip of a float is a bounded perturbation, not a jump.
    assert 0.5 < abs(float(m)) / abs(float(v)) < 2.0
    e = faults.bitflip_element(v, bit=23)
    assert np.isfinite(e) and e != v
    with pytest.raises(ValueError):
        faults.bitflip_element(np.float16(1.0))
    with pytest.raises(ValueError):
        faults.bitflip_element(v, bit_class="nope")


def test_parse_bitflip_spec_forms_and_errors():
    assert faults.parse_bitflip_spec("100") == (100, "w", None)
    assert faults.parse_bitflip_spec("50:r") == (50, "r", None)
    assert faults.parse_bitflip_spec("50:Ap:29") == (50, "Ap", 29)
    for bad in ("x", "10:q", "10:w:z", "1:2:3:4"):
        with pytest.raises(ValueError):
            faults.parse_bitflip_spec(bad)


def test_inject_bitflip_is_deterministic_and_single_element():
    ops, rhs = _f64_ops()
    s = _run(ops, rhs, 20)
    s1 = faults.inject_bitflip(s, "r", seed=3)
    s2 = faults.inject_bitflip(s, "r", seed=3)
    d1 = np.asarray(s1.r) - np.asarray(s.r)
    assert np.array_equal(np.asarray(s1.r), np.asarray(s2.r))
    assert np.count_nonzero(d1) == 1
    assert np.isfinite(np.asarray(s1.r)).all()
    # Untouched buffers stay untouched.
    assert np.array_equal(np.asarray(s1.w), np.asarray(s.w))
    with pytest.raises(ValueError):
        faults.inject_bitflip(s, "nope")


def test_inject_bitflip_member_isolates_batchmates():
    State = types.SimpleNamespace
    w = np.outer(np.arange(3.0) + 1.0,
                 np.ones(36)).reshape(3, 6, 6)
    state = State(w=w.copy())
    state._replace = lambda **kw: State(**{**vars(state), **kw})
    out = faults.inject_bitflip(state, "w", member=1, seed=0)
    delta = np.asarray(out.w) - w
    assert np.count_nonzero(delta[1]) == 1
    assert not delta[0].any() and not delta[2].any()


# -- the invariants (integrity/probe) -----------------------------------


def test_drift_invariant_clean_vs_flipped():
    ops, rhs = _f64_ops()
    s = _run(ops, rhs, 20)
    tol = probe.default_verify_tol("float64")
    assert not bool(probe.drift_exceeds(ops, s.w, s.r, rhs, tol))
    bad = faults.inject_bitflip(s, "r", seed=0)
    assert bool(probe.drift_exceeds(ops, bad.w, bad.r, rhs, tol))
    confirmed, drift = probe.recheck_state(ops, bad.w, bad.r, rhs, tol)
    assert confirmed and drift > tol


def test_drift_nonfinite_is_a_verdict_not_a_blind_spot():
    """An overflowed buffer must read as corruption: NaN/Inf compares
    would silently return False and the probe would go blind on exactly
    the largest corruptions."""
    import jax.numpy as jnp

    ops, rhs = _f64_ops()
    s = _run(ops, rhs, 10)
    blown = s._replace(w=jnp.full_like(s.w, jnp.inf))
    assert bool(probe.drift_exceeds(ops, blown.w, blown.r, rhs, 1e-6))
    confirmed, _ = probe.recheck_state(ops, blown.w, blown.r, rhs, 1e-6)
    assert confirmed


def test_abft_checksum_row_identity():
    import jax.numpy as jnp

    ops, rhs = _f64_ops()
    s = _run(ops, rhs, 15)
    colsum = probe.abft_colsum(ops, rhs)
    p = ops.exchange(s.p)
    Ap = ops.apply_A(p)
    assert not bool(probe.abft_drift_exceeds(colsum, p, Ap, 1e-9))
    # A corrupted stencil application breaks the identity immediately.
    bad = Ap.at[7, 9].add(1e-3 * float(jnp.abs(Ap).max()) + 1e-6)
    assert bool(probe.abft_drift_exceeds(colsum, p, bad, 1e-9))


def test_default_tols_are_dtype_aware():
    assert probe.default_verify_tol("float64") < probe.default_verify_tol(
        "float32") < probe.default_verify_tol("bfloat16")


# -- the campaign: seeded flips across buffers/iterations/precisions ----

# Injection points per buffer. The p (search direction) rows start at
# 25 for f32: the collapse a silent flip produces grows as the
# direction decays under the flip's structural magnitude cap, and
# before ~iteration 20 a scaled-f32 flip lands inside CG's own
# step-to-step range (≤2.1× vs clean ≤2.5×) — the bounded-harm regime
# pinned below, not a detection miss. f64 runs unscaled, where the
# reachable flip is astronomically larger; every point detects.
_CAMPAIGN = {
    "float32": {"w": (10, 40), "r": (10, 40), "p": (25, 40),
                "Ap": (10, 40)},
    "float64": {"w": (10, 40), "r": (10, 40), "p": (10, 40),
                "Ap": (10, 40)},
}


@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_seeded_bitflip_campaign_detects_and_recovers(dtype):
    """Every injected exponent-class flip is detected within
    verify_every iterations, recovered by a verified restart (never a
    precision escalation), and the solve converges — with zero false
    alarms across the whole campaign."""
    for buffer, ats in _CAMPAIGN[dtype].items():
        for at in ats:
            for seed in (0, 1):
                metrics.reset()
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore", RuntimeWarning)
                    res = pcg_solve_resilient(
                        PROBLEM, dtype=dtype, chunk=5, verify_every=5,
                        on_chunk=faults.bitflip_per_solve_hook(
                            at, buffer=buffer, seed=seed))
                tag = (dtype, buffer, at, seed)
                assert metrics.get("integrity.detections") >= 1, tag
                assert metrics.get("integrity.verified_restarts") >= 1, tag
                assert metrics.get("integrity.false_alarms") == 0, tag
                assert metrics.get("resilient.escalations") == 0, tag
                assert int(res.flag) == FLAG_CONVERGED, tag
                assert res.restarts >= 1, tag


def test_early_f32_direction_flip_is_bounded_harm():
    """The carve-out, proven harmless: an early scaled-f32 flip in p
    keeps the recurrence consistent (w and r advance in step with the
    corrupted direction), so CG provably converges to the correct
    answer — merely slower. No restart is needed and none fires."""
    golden = pcg_solve_resilient(PROBLEM, dtype="float32", chunk=5)
    for seed in (0, 1):
        metrics.reset()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            res = pcg_solve_resilient(
                PROBLEM, dtype="float32", chunk=5, verify_every=5,
                on_chunk=faults.bitflip_per_solve_hook(
                    10, buffer="p", seed=seed))
        assert int(res.flag) == FLAG_CONVERGED
        assert metrics.get("integrity.false_alarms") == 0
        err = np.abs(np.asarray(res.w) - np.asarray(golden.w)).max()
        scale = np.abs(np.asarray(golden.w)).max()
        assert err < 1e-3 * scale, (seed, err, scale)


def test_mantissa_flip_never_false_alarms_the_recovery():
    """Mantissa-MSB flips (≤2× perturbations) are best-effort by
    contract; what IS guaranteed: the solve converges and nothing is
    ever classified false alarm on a real injection that goes
    undetected (an undetected flip simply never reaches the driver)."""
    for buffer in ("w", "r"):
        metrics.reset()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            res = pcg_solve_resilient(
                PROBLEM, dtype="float64", chunk=5, verify_every=5,
                on_chunk=faults.bitflip_per_solve_hook(
                    20, buffer=buffer, bit_class="mantissa", seed=0))
        assert int(res.flag) == FLAG_CONVERGED
        assert metrics.get("integrity.false_alarms") == 0


# -- zero false alarms on clean goldens ---------------------------------


@pytest.mark.parametrize(
    "M,N,weighted,expected",
    [(10, 10, False, {17}), (20, 20, False, {31}),
     (40, 40, True, {50})],
)
@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_clean_goldens_verified_keep_counts(M, N, weighted, expected,
                                            dtype):
    r = pcg_solve(Problem(M=M, N=N, weighted_norm=weighted),
                  dtype=dtype, verify_every=5)
    assert int(r.flag) == FLAG_CONVERGED
    assert int(r.iterations) in expected


@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_clean_resilient_verified_zero_verdicts(dtype):
    base = pcg_solve_resilient(PROBLEM, dtype=dtype, chunk=10)
    metrics.reset()
    ver = pcg_solve_resilient(PROBLEM, dtype=dtype, chunk=10,
                              verify_every=5)
    assert int(ver.iterations) == int(base.iterations)
    assert ver.restarts == 0
    assert metrics.get("integrity.detections") == 0
    assert metrics.get("integrity.false_alarms") == 0
    assert metrics.get("integrity.checks") >= 1   # boundary rechecks ran


def test_clean_geometry_solves_verified_no_false_alarms():
    from poisson_tpu.geometry import Ellipse, Rectangle

    for geom in (Ellipse(cx=0.1, cy=0.0, rx=0.7, ry=0.4),
                 Rectangle(-0.6, -0.3, 0.5, 0.3)):
        base = pcg_solve(PROBLEM, dtype="float32", geometry=geom)
        ver = pcg_solve(PROBLEM, dtype="float32", geometry=geom,
                        verify_every=5)
        assert int(ver.flag) == FLAG_CONVERGED
        assert int(ver.iterations) == int(base.iterations)


# -- off means off: byte-identical HLO, bit-for-bit counts --------------


def test_verify_off_hlo_is_byte_identical_to_pre_integrity_body():
    """``verify_every=0`` must lower to the EXACT pre-integrity
    program: the fused loop built from today's body is compared against
    one built from a verbatim copy of the pre-PR iteration body —
    compiled HLO equal byte-for-byte (debug metadata aside). This is
    what makes the layer shippable default-off."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from poisson_tpu.solvers.pcg import (
        _DENOM_TOL,
        FLAG_BREAKDOWN,
        FLAG_NONE,
        FLAG_NONFINITE,
        FLAG_STAGNATED,
        PCGState,
        _select,
    )

    p = Problem(M=24, N=24)
    ops, rhs = _f64_ops(p)
    delta, h1, h2 = p.delta, p.h1, p.h2
    weighted_norm = p.weighted_norm

    def historical_body(s):
        # The pre-integrity make_pcg_body inner body, copied VERBATIM
        # (stream/stagnation off — their flag-off branches are theirs
        # to pin).
        p_ = ops.exchange(s.p)
        Ap = ops.apply_A(p_)
        denom = ops.dot(Ap, p_)
        degenerate = jnp.abs(denom) < _DENOM_TOL
        alpha = s.zr / jnp.where(degenerate, 1.0, denom)

        dw = alpha * p_
        w_new = s.w + dw
        r_new = s.r - alpha * Ap
        sq = ops.sqnorm(dw)
        diff = (jnp.sqrt(sq * (h1 * h2)) if weighted_norm
                else jnp.sqrt(sq))

        z_new = ops.apply_Dinv(r_new)
        zr_new = ops.dot(z_new, r_new)
        converged = diff < delta

        beta = zr_new / jnp.where(s.zr == 0.0, 1.0, s.zr)
        p_new = z_new + beta * p_

        nonfinite = ~(jnp.isfinite(diff) & jnp.isfinite(zr_new))
        improved = diff < s.best
        best_new = jnp.minimum(s.best, diff)
        stall_new = jnp.where(improved, 0, s.stall + 1).astype(jnp.int32)
        stagnated = jnp.asarray(False)
        flag = jnp.where(
            nonfinite, FLAG_NONFINITE,
            jnp.where(converged, FLAG_CONVERGED,
                      jnp.where(stagnated, FLAG_STAGNATED, FLAG_NONE)),
        ).astype(jnp.int32)

        candidate = PCGState(
            k=s.k + 1,
            done=degenerate | converged | nonfinite | stagnated,
            w=w_new, r=r_new, z=z_new, p=p_new,
            zr=zr_new, diff=diff,
            flag=flag, best=best_new, stall=stall_new,
        )
        kept = s._replace(
            k=s.k + 1, done=jnp.asarray(True),
            flag=jnp.asarray(FLAG_BREAKDOWN, jnp.int32),
        )
        return _select(degenerate, kept, candidate)

    current_body = make_pcg_body(
        ops, delta=delta, weighted_norm=weighted_norm, h1=h1, h2=h2,
        verify_every=0,
    )

    def hlo(body):
        def loop(r0):
            def cond(s):
                return (~s.done) & (s.k < p.iteration_cap)

            return lax.while_loop(cond, body, init_state(ops, r0))

        from poisson_tpu.contracts.hlo import strip_hlo_metadata

        txt = jax.jit(loop).lower(rhs).compile().as_text()
        return strip_hlo_metadata(txt)

    assert hlo(current_body) == hlo(historical_body)


# -- per-member masking: one corrupted lane, innocents untouched --------


def test_masked_per_member_detection_in_a_running_bucket():
    from poisson_tpu.solvers.lanes import LaneBatch

    prob = Problem(M=32, N=32)
    gates = {"victim": 1.0, "inn-0": 1.1, "inn-1": 1.2}
    solo = {mid: pcg_solve(prob, dtype="float32", rhs_gate=g,
                           verify_every=5)
            for mid, g in gates.items()}
    lb = LaneBatch(prob, bucket=4, dtype="float32", chunk=10,
                   verify_every=5)
    lanes = {mid: lb.splice(mid, rhs_gate=g) for mid, g in gates.items()}
    lb.step()                      # everyone ~10 iterations deep
    faults.bitflip_lane(lb, lanes["victim"], buffer="w", seed=0)
    for _ in range(60):
        if all(v["done"] for v in lb.lane_view()
               if v["member_id"] is not None):
            break
        lb.step()
    out = {v["member_id"]: v for v in lb.lane_view()
           if v["member_id"] is not None}
    assert out["victim"]["flag"] == FLAG_INTEGRITY
    # Detection within one verify stride of the flip landing.
    assert out["victim"]["k"] <= 10 + 5
    for mid in ("inn-0", "inn-1"):
        assert out[mid]["flag"] == FLAG_CONVERGED, out[mid]
        assert out[mid]["k"] == int(solo[mid].iterations), mid
    res = lb.retire(lanes["victim"])
    assert res.flag == FLAG_INTEGRITY and res.member_id == "victim"


def test_batched_verified_clean_matches_unverified():
    from poisson_tpu.solvers.batched import solve_batched

    prob = Problem(M=32, N=32)
    base = solve_batched(prob, rhs_gates=[1.0, 1.3, 0.8],
                         dtype="float32")
    ver = solve_batched(prob, rhs_gates=[1.0, 1.3, 0.8],
                        dtype="float32", verify_every=5)
    assert [int(k) for k in ver.iterations] == [
        int(k) for k in base.iterations]
    assert all(int(f) == FLAG_CONVERGED for f in ver.flag)


# -- service response: typed outcome, suspect-cohort defense ------------


def test_suspect_cohort_defense_arms_after_first_strike():
    from poisson_tpu.serve import (
        ERROR_INTEGRITY,
        IntegrityPolicy,
        ServicePolicy,
        SolveService,
    )

    svc = SolveService(ServicePolicy(integrity=IntegrityPolicy()))
    assert svc._verify_params() == (0, None)
    # An integrity-class retry defends itself even before any taint.
    entry = types.SimpleNamespace(last_failure=ERROR_INTEGRITY)
    assert svc._verify_params([entry])[0] == 25
    svc._taint_suspect_hw()
    assert svc._verify_params()[0] == 25
    assert metrics.get("serve.integrity.suspect_cohorts") == 1
    svc._taint_suspect_hw()    # idempotent: cohorts, not detections
    assert metrics.get("serve.integrity.suspect_cohorts") == 1
    # Always-on policy wins over the suspect stride.
    svc2 = SolveService(ServicePolicy(
        integrity=IntegrityPolicy(verify_every=7, verify_tol=1e-4)))
    assert svc2._verify_params() == (7, 1e-4)


@pytest.mark.parametrize("name", [
    "sdc-verified-restart",
    "sdc-batch-member-isolated",
    "sdc-refill-splice",
])
def test_sdc_chaos_scenarios_keep_the_ledger(name):
    from poisson_tpu.testing import chaos

    rep = chaos.run_scenario(name, seed=0)
    assert rep["ok"], (name, rep["checks"])
    assert rep["invariant"]["lost"] == 0
    assert len(chaos.scenario_names()) >= 24


def test_chaos_list_groups_include_integrity():
    from poisson_tpu.testing import chaos

    groups = chaos.scenario_groups()
    assert set(groups["integrity"]) == {
        "sdc-verified-restart", "sdc-batch-member-isolated",
        "sdc-refill-splice"}
    flat = [n for names in groups.values() for n in names]
    assert sorted(flat) == sorted(chaos.scenario_names())


# -- sentinel cohort/direction pins -------------------------------------


def _regress():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "regress", pathlib.Path(__file__).resolve().parents[1]
        / "benchmarks" / "regress.py")
    regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(regress)
    return regress


def test_regress_verify_every_splits_cohorts():
    regress = _regress()

    def rec(verify_every, value):
        return regress.record_from_result({
            "metric": "mlups",
            "value": value,
            "detail": {"grid": [400, 600], "dtype": "float32",
                       "backend": "xla", "devices": 1,
                       "platform": "cpu",
                       **({"verify_every": verify_every}
                          if verify_every else {})},
        }, source="test")

    verified = rec(25, 70.0)
    clean = rec(None, 100.0)
    assert regress.cohort_key(verified) != regress.cohort_key(clean)
    assert regress.cohort_key(rec(25, 72.0)) == regress.cohort_key(
        verified)
    # A verified run paying its probe overhead among unverified
    # baselines must NOT alarm: the cohorts never meet.
    records = [rec(None, 100.0 + i) for i in range(4)] + [verified]
    verdict = regress.evaluate(records)
    assert all(r["classification"] != "regression"
               for r in verdict["records"]), verdict
