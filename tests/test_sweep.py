"""Benchmark sweep harness: table generation and curve output."""

import csv
import pathlib
import sys

_BENCH_DIR = str(pathlib.Path(__file__).resolve().parents[1] / "benchmarks")


def test_roofline_smoke(capsys):
    """roofline.py runs end-to-end on CPU (interpret kernels) and emits a
    well-formed report with solver rows and a stream ceiling."""
    import json

    if _BENCH_DIR not in sys.path:
        sys.path.insert(0, _BENCH_DIR)
    import roofline

    old_argv = sys.argv
    sys.argv = ["roofline.py", "40", "40", "--iters", "40",
                "--backend", "fused,ca"]
    try:
        assert roofline.main() == 0
    finally:
        sys.argv = old_argv
    out = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(out)
    assert rec["platform"] == "cpu"
    assert rec["solver"]
    # The pallas kernels are version-gated: on an installation whose
    # jax.experimental.pallas lacks the APIs they need, every solver
    # row degrades to a typed error row. Skip audibly (naming the gap)
    # instead of failing — the mlups/model assertions below are about
    # the roofline report shape, not about pallas availability.
    errors = [row.get("error") for row in rec["solver"]]
    if all(errors):
        import pytest

        pytest.skip(f"pallas kernels unavailable here: {errors[0]}")
    assert "mlups" in rec["solver"][0]
    by_backend = {row["backend"]: row for row in rec["solver"]}
    assert set(by_backend) == {"fused", "ca"}
    # The CA pass model must undercut the fused one at the same geometry
    # (the whole point of the s=2 restructuring).
    assert (by_backend["ca"]["model_passes"]
            < by_backend["fused"]["model_passes"])


def test_sweep_tiny_grid(tmp_path, capsys):
    sys.path.insert(0, _BENCH_DIR)
    try:
        import sweep
    finally:
        sys.path.remove(_BENCH_DIR)

    out = tmp_path / "table.md"
    curve = tmp_path / "curve.csv"
    rc = sweep.main([
        "--grids", "20x20", "--backends", "xla,native", "--threads", "1",
        "--repeat", "1", "--out", str(out),
        "--curve", "20x20:40", "--curve-out", str(curve),
    ])
    assert rc == 0

    table = out.read_text()
    assert "| xla |" in table and "| native |" in table
    assert "20x20" in table

    with open(curve) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 40
    assert float(rows[0]["diff_norm"]) > float(rows[-1]["diff_norm"])
