"""Geometric multigrid preconditioning (``poisson_tpu.mg``).

The contract under test, layer by layer:

- **off means off** — ``preconditioner="jacobi"`` (the default) lowers
  to the byte-identical historical solve program and keeps the golden
  iteration counts bit-for-bit;
- **the cycle works** — two-grid contraction < 0.2 on the literature's
  model problem, and the V-cycle *apply* is bit-identical under vmap
  (the parity contract the batched/lane drivers rest on);
- **the iteration wall breaks** — MG counts stay ~flat (within 2×)
  across 100×150 → 200×300 → 400×600 where Jacobi's roughly double;
- **every geometry family gates** — the manufactured-solution L2 floor
  holds under MG for each closed-form family (the PR 9 rule verbatim);
- **the rails hold** — batched/lane/chunked/resilient parity, verified
  clean solves with zero false alarms at the MG-calibrated guard
  ratios, bit-flip detection + verified restart, serve cohort split,
  and sentinel cohort/direction pins.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.mg import (
    DEFAULT_MG,
    MGConfig,
    coarsen_a,
    coarsen_b,
    device_hierarchy,
    plan_levels,
    reset_hierarchy_cache,
    v_cycle,
    validate_mg_problem,
)
from poisson_tpu.solvers.pcg import (
    FLAG_CONVERGED,
    host_setup,
    pcg_solve,
)

jax.config.update("jax_enable_x64", True)

pytestmark = pytest.mark.mg


# -- level planning and coefficient coarsening --------------------------


def test_plan_levels_bench_grids_share_coarsest():
    """Every published bench grid bottoms out at the SAME 50×75
    coarsest level — what makes their iteration counts comparable."""
    for M, N in ((400, 600), (800, 1200), (1600, 2400), (3200, 4800)):
        assert plan_levels(M, N)[-1] == (50, 75)
    assert plan_levels(400, 600) == (
        (400, 600), (200, 300), (100, 150), (50, 75))


def test_validate_mg_problem_rejects_uncoarsenable():
    with pytest.raises(ValueError, match="coarsens"):
        validate_mg_problem(Problem(M=33, N=33))
    with pytest.raises(ValueError, match="coarsens"):
        validate_mg_problem(Problem(M=10, N=10))
    assert len(validate_mg_problem(Problem(M=40, N=40))) >= 2


def test_coarsen_constant_fields_exactly():
    a = np.full((65, 97), 3.5)
    ac = coarsen_a(a)
    assert ac.shape == (33, 49)
    np.testing.assert_array_equal(ac, 3.5)
    bc = coarsen_b(np.full((65, 97), 0.25))
    np.testing.assert_array_equal(bc, 0.25)


def test_coarsening_keeps_penalty_stiff():
    """The fictitious region's ~1/ε blend must survive coarsening, or
    the coarse correction would let the solution leak through the
    boundary: outside-the-ellipse coarse faces stay within 2× of the
    fine penalty scale."""
    p = Problem(M=64, N=64)
    from poisson_tpu.solvers.pcg import host_fields64

    a64, _, _, _ = host_fields64(p, False)
    ac = coarsen_a(np.asarray(a64))
    # Node far outside the ellipse on the coarse grid (corner region).
    assert ac[3, 3] > 0.5 / p.eps


# -- off means off: the default path is untouched -----------------------


def test_default_jacobi_path_hlo_byte_identical():
    """``pcg_solve``'s default path must still compile the EXACT
    historical program: the jitted ``_solve`` internals vs a verbatim
    local reconstruction, compiled HLO equal byte-for-byte (debug
    metadata aside) — with the mg module imported and used first, so
    nothing about loading the subsystem can perturb the default."""
    import poisson_tpu.solvers.pcg as pcg_mod
    from poisson_tpu.solvers.pcg import (
        PCGResult,
        pcg_loop,
        single_device_ops,
    )

    p = Problem(M=20, N=24)
    pcg_solve(p, preconditioner="mg")   # mg traffic first, on purpose
    a, b, rhs, aux = host_setup(p, "float64", False)

    current_txt = pcg_mod._solve.lower(
        p, False, 0, 0, 0.0, False, 0,
        a, b, rhs, aux).compile().as_text()

    # Named ``_solve`` so both lowerings produce the same HLO module
    # name ("jit__solve") and with it identical instruction numbering.
    def _solve(a, b, rhs, aux):
        ops = single_device_ops(p, a, b, aux)
        s = pcg_loop(
            ops, rhs, delta=p.delta, max_iter=p.iteration_cap,
            weighted_norm=p.weighted_norm, h1=p.h1, h2=p.h2,
            stream_every=0, verify_every=0, verify_tol=0.0,
            verify_abft=False,
        )
        return PCGResult(w=s.w, iterations=s.k, diff=s.diff,
                         residual_dot=s.zr, flag=s.flag)

    historical_txt = jax.jit(_solve).lower(
        a, b, rhs, aux).compile().as_text()

    from poisson_tpu.contracts.hlo import strip_hlo_metadata

    assert strip_hlo_metadata(current_txt) \
        == strip_hlo_metadata(historical_txt)


@pytest.mark.parametrize("M,N,weighted,expected", [
    (10, 10, False, 17), (20, 20, False, 31), (40, 40, True, 50),
])
def test_golden_counts_bit_for_bit_with_explicit_jacobi(M, N, weighted,
                                                        expected):
    r = pcg_solve(Problem(M=M, N=N, weighted_norm=weighted),
                  preconditioner="jacobi")
    assert int(r.iterations) == expected
    default = pcg_solve(Problem(M=M, N=N, weighted_norm=weighted))
    assert bool(jnp.all(default.w == r.w))


def test_unknown_preconditioner_is_loud():
    with pytest.raises(ValueError, match="unknown preconditioner"):
        pcg_solve(Problem(M=20, N=20), preconditioner="amg")


# -- the cycle itself ---------------------------------------------------


def test_two_grid_convergence_factor_under_020():
    """The satellite check: smoothing + coarse correction contract by
    < 0.2 per cycle on the isotropic model problem (exact dense coarse
    solve — the two-grid operator of the textbooks)."""
    from poisson_tpu.mg.selfcheck import two_grid_factor

    assert two_grid_factor(64, 64, max_levels=2) < 0.2


def test_deep_vcycle_factor_stays_bounded():
    from poisson_tpu.mg.selfcheck import two_grid_factor

    assert two_grid_factor(64, 64, max_levels=16) < 0.25


def test_vcycle_apply_bit_parity_under_vmap():
    """The MG APPLY parity contract: one V-cycle produces bit-identical
    output solo and vmapped — the reduction-order guarantee the
    batched/lane drivers' per-member trajectories rest on (the coarse
    dense matvec is a broadcast-multiply + trailing-axis reduce for
    exactly this reason)."""
    p = Problem(M=64, N=64)
    a, b, rhs, aux = host_setup(p, "float32", True)
    reset_hierarchy_cache()
    hier = device_hierarchy(p, "float32", True)
    assert hier.coarse_inv is not None   # the risky reduction is live

    f = lambda r: v_cycle(hier, r, p.h1, p.h2, DEFAULT_MG)
    solo = jax.jit(f)(rhs)
    stacked = jax.jit(jax.vmap(f))(jnp.stack([rhs, rhs * 1.3, rhs * 0.2]))
    assert bool(jnp.all(stacked[0] == solo))
    solo3 = jax.jit(f)(rhs * 0.2)
    assert bool(jnp.all(stacked[2] == solo3))


def test_mg_solves_same_problem_as_jacobi():
    p = Problem(M=64, N=96)
    rj = pcg_solve(p)
    rm = pcg_solve(p, preconditioner="mg")
    assert int(rm.flag) == FLAG_CONVERGED
    assert float(rm.diff) < p.delta
    assert int(rm.iterations) * 3 <= int(rj.iterations)
    np.testing.assert_allclose(np.asarray(rm.w), np.asarray(rj.w),
                               atol=5e-5)


# -- iteration flatness: the wall actually breaks -----------------------


def test_iteration_counts_flat_across_resolutions():
    """Acceptance criterion: MG counts within 2× across
    100×150 → 200×300 → 400×600 while Jacobi's grow ~2× per step."""
    mg_counts, jac_counts = [], []
    for M, N in ((100, 150), (200, 300), (400, 600)):
        p = Problem(M=M, N=N)
        jac_counts.append(int(pcg_solve(p, dtype=jnp.float32).iterations))
        rm = pcg_solve(p, dtype=jnp.float32, preconditioner="mg")
        assert int(rm.flag) == FLAG_CONVERGED
        mg_counts.append(int(rm.iterations))
    assert max(mg_counts) <= 2 * min(mg_counts), mg_counts
    assert jac_counts[1] >= 1.7 * jac_counts[0]
    assert jac_counts[2] >= 1.7 * jac_counts[1]
    assert mg_counts[-1] * 10 <= jac_counts[-1]


# -- geometry families gate at the floor --------------------------------


@pytest.mark.parametrize("family", [
    "ellipse", "ellipse-offset", "rectangle", "polygon", "union",
    "intersection", "difference", "sdf",
])
def test_manufactured_floor_per_family_under_mg(family):
    """The PR 9 gating rule generalized verbatim: each family's
    manufactured-solution L2 must land at (essentially) the same floor
    under MG as under Jacobi — the preconditioner changes the path to
    the answer, never the answer."""
    from poisson_tpu.geometry.manufactured import (
        case_by_name,
        manufactured_error,
    )

    case = case_by_name(family)
    ej = manufactured_error(case, 64, 96)
    em = manufactured_error(case, 64, 96, preconditioner="mg")
    assert em["flag"] == FLAG_CONVERGED
    assert em["rel"] <= ej["rel"] * 1.1 + 1e-12
    assert em["iterations"] < ej["iterations"]


def test_mg_geometry_solo_solve():
    from poisson_tpu.geometry import Ellipse

    p = Problem(M=64, N=64)
    g = Ellipse(cx=0.1, cy=0.0, rx=0.7, ry=0.4)
    rm = pcg_solve(p, preconditioner="mg", geometry=g)
    rj = pcg_solve(p, geometry=g)
    assert int(rm.flag) == FLAG_CONVERGED
    np.testing.assert_allclose(np.asarray(rm.w), np.asarray(rj.w),
                               atol=5e-5)


# -- batched / lane / chunked / resilient parity ------------------------


def test_batched_mg_members_match_solo():
    """Iteration counts and flags exactly; iterates to a few ULPs (the
    FMA-contraction caveat documented on ``solve_batched``); and the MG
    bucket is its own executable family in the bucket cache."""
    from poisson_tpu.obs import metrics
    from poisson_tpu.solvers.batched import (
        reset_bucket_cache,
        solve_batched,
    )

    metrics.reset()
    reset_bucket_cache()
    p = Problem(M=64, N=64)
    gates = [1.0, 1.3, 0.7]
    solo = [pcg_solve(p, dtype=jnp.float32, preconditioner="mg",
                      rhs_gate=g) for g in gates]
    bat = solve_batched(p, rhs_gates=gates, dtype=jnp.float32,
                        preconditioner="mg")
    for i, s in enumerate(solo):
        assert int(bat.iterations[i]) == int(s.iterations)
        assert int(bat.flag[i]) == int(s.flag) == FLAG_CONVERGED
        np.testing.assert_allclose(np.asarray(bat.w[i]),
                                   np.asarray(s.w), atol=1e-5)
    # Same bucket, jacobi arm: a DIFFERENT executable family (both
    # counted as misses — the mg marker is part of the key).
    solve_batched(p, rhs_gates=gates, dtype=jnp.float32)
    assert metrics.get("batched.bucket_cache.misses") == 2
    # Re-dispatching the mg bucket is a hit.
    solve_batched(p, rhs_gates=[2.0, 0.5, 1.1], dtype=jnp.float32,
                  preconditioner="mg")
    assert metrics.get("batched.bucket_cache.hits") == 1


def test_lanes_mg_splice_step_retire():
    from poisson_tpu.solvers.lanes import LaneBatch

    p = Problem(M=64, N=64)
    solo = {g: pcg_solve(p, dtype=jnp.float32, preconditioner="mg",
                         rhs_gate=g) for g in (1.0, 1.3)}
    lb = LaneBatch(p, 2, dtype=jnp.float32, chunk=3,
                   preconditioner="mg")
    lb.splice("a", 1.0)
    lb.step()                      # "b" joins a RUNNING program
    lb.splice("b", 1.3)
    results = {}
    while lb.occupied():
        for v in lb.lane_view():
            if v["member_id"] is not None and v["done"]:
                res = lb.retire(v["lane"])
                results[res.member_id] = res
        if lb.occupied():
            lb.step()
    ref = {"a": solo[1.0], "b": solo[1.3]}
    for mid, res in results.items():
        assert res.iterations == int(ref[mid].iterations)
        assert res.flag == FLAG_CONVERGED
        np.testing.assert_allclose(np.asarray(res.w),
                                   np.asarray(ref[mid].w), atol=1e-5)


def test_lanes_mg_rejects_multi_geometry():
    from poisson_tpu.solvers.lanes import LaneBatch

    with pytest.raises(ValueError, match="per-lane"):
        LaneBatch(Problem(M=64, N=64), 2, preconditioner="mg",
                  multi_geometry=True)


def test_batched_mg_rejects_geometries():
    from poisson_tpu.geometry import Ellipse
    from poisson_tpu.solvers.batched import solve_batched

    with pytest.raises(ValueError, match="co-batch"):
        solve_batched(Problem(M=64, N=64), rhs_gates=[1.0],
                      preconditioner="mg",
                      geometries=[Ellipse(cx=0, cy=0, rx=0.5, ry=0.3)])


def test_chunked_and_resilient_mg_bitwise_vs_one_shot():
    from poisson_tpu.solvers.checkpoint import pcg_solve_chunked
    from poisson_tpu.solvers.resilient import pcg_solve_resilient

    p = Problem(M=64, N=64)
    one = pcg_solve(p, dtype=jnp.float32, preconditioner="mg")
    ch = pcg_solve_chunked(p, chunk=3, dtype=jnp.float32,
                           preconditioner="mg")
    assert bool(jnp.all(ch.w == one.w))
    assert int(ch.iterations) == int(one.iterations)
    rs = pcg_solve_resilient(p, chunk=4, dtype=jnp.float32,
                             preconditioner="mg")
    assert bool(jnp.all(rs.w == one.w))
    assert rs.restarts == 0


def test_checkpoint_fingerprint_refuses_cross_preconditioner_resume(
        tmp_path):
    """A Jacobi-written state must never resume under MG (two different
    Krylov recurrences): the fingerprint carries the preconditioner."""
    from poisson_tpu.solvers.checkpoint import pcg_solve_checkpointed

    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    pcg_solve_checkpointed(p, path, chunk=10, keep_checkpoint=True)
    with pytest.raises(ValueError, match="different problem"):
        pcg_solve_checkpointed(p, path, chunk=10, keep_checkpoint=True,
                               preconditioner="mg")


# -- integrity: re-measured guard ratios --------------------------------


def test_mg_verified_clean_solve_no_false_alarms():
    """The MG-calibrated collapse/jump ratios: a clean verified MG
    solve keeps its unverified iteration count with zero integrity
    verdicts — the Jacobi-calibrated ratios WOULD false-alarm here
    (clean MG one-step drops measure up to ~29×, see
    integrity.probe.DEFAULT_VERIFY_COLLAPSE_MG)."""
    p = Problem(M=100, N=150)   # the worst measured clean collapse grid
    plain = pcg_solve(p, dtype=jnp.float32, preconditioner="mg")
    ver = pcg_solve(p, dtype=jnp.float32, preconditioner="mg",
                    verify_every=3)
    assert int(ver.flag) == FLAG_CONVERGED
    assert int(ver.iterations) == int(plain.iterations)


def test_jacobi_ratios_would_false_alarm_on_clean_mg():
    """The re-measurement mattered: the same clean solve run with the
    Jacobi collapse ratio trips the guard — direction pin that the
    preconditioner-specific calibration is load-bearing."""
    from poisson_tpu.integrity.probe import (
        DEFAULT_VERIFY_COLLAPSE,
        DEFAULT_VERIFY_COLLAPSE_MG,
        default_verify_collapse,
    )

    assert default_verify_collapse("mg") == DEFAULT_VERIFY_COLLAPSE_MG
    assert default_verify_collapse("jacobi") == DEFAULT_VERIFY_COLLAPSE
    assert DEFAULT_VERIFY_COLLAPSE_MG > 28.6   # the measured clean max
    assert DEFAULT_VERIFY_COLLAPSE < 28.6      # jacobi's line is below


def test_mg_resilient_detects_bitflip_and_recovers():
    import warnings

    from poisson_tpu.obs import metrics
    from poisson_tpu.solvers.resilient import pcg_solve_resilient
    from poisson_tpu.testing.faults import bitflip_per_solve_hook

    metrics.reset()
    p = Problem(M=64, N=64)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        r = pcg_solve_resilient(
            p, chunk=2, verify_every=1, preconditioner="mg",
            on_chunk=bitflip_per_solve_hook(4, buffer="w", seed=1))
    assert int(r.flag) == FLAG_CONVERGED
    assert r.restarts >= 1
    assert metrics.get("integrity.detections") >= 1
    assert metrics.get("integrity.verified_restarts") >= 1
    assert metrics.get("resilient.escalations") == 0


# -- hierarchy cache + cost model ---------------------------------------


def test_hierarchy_cache_counters():
    from poisson_tpu.obs import metrics

    metrics.reset()
    reset_hierarchy_cache()
    p = Problem(M=40, N=40)
    device_hierarchy(p, "float32", True)
    device_hierarchy(p, "float32", True)
    device_hierarchy(p.with_(f_val=2.0), "float32", True)  # normalized
    assert metrics.get("mg.hierarchy_cache.misses") == 1
    assert metrics.get("mg.hierarchy_cache.hits") == 2


def test_mg_vcycle_cost_model():
    from poisson_tpu.obs import metrics
    from poisson_tpu.obs.costs import mg_vcycle_cost

    metrics.reset()
    small = mg_vcycle_cost(100, 150, 4)
    large = mg_vcycle_cost(400, 600, 4)
    assert small["coarse_dense"] and large["coarse_dense"]
    assert large["bytes"] > small["bytes"]
    assert large["levels"] == 4
    assert metrics.snapshot()["gauges"]["cost.mg.bytes_per_cycle"] \
        == large["bytes"]
    # The dense coarse matvec is a constant term: fine-equivalent
    # passes SHRINK with resolution (the win grows at the large end).
    assert large["passes_fine_equivalent"] < small["passes_fine_equivalent"]


# -- serve: cohort split and outcomes -----------------------------------


@pytest.mark.parametrize("scheduling", ["drain", "continuous"])
def test_serve_mg_and_jacobi_cohorts_split(scheduling):
    from poisson_tpu.serve import (
        ServicePolicy,
        SolveRequest,
        SolveService,
    )

    p = Problem(M=32, N=32)
    svc = SolveService(ServicePolicy(capacity=16, max_batch=4,
                                     scheduling=scheduling), seed=0)
    for i in range(3):
        svc.submit(SolveRequest(request_id=f"m{i}", problem=p,
                                rhs_gate=1.0 + i / 10,
                                preconditioner="mg"))
        svc.submit(SolveRequest(request_id=f"j{i}", problem=p,
                                rhs_gate=1.0 + i / 10))
    outs = svc.drain()
    stats = svc.stats()
    assert stats["lost"] == 0
    by_id = {o.request_id: o for o in outs}
    for i in range(3):
        assert by_id[f"m{i}"].converged and by_id[f"j{i}"].converged
        # MG requests converge in far fewer iterations — and the split
        # cohort is visible in the breaker registry.
        assert by_id[f"m{i}"].iterations * 3 <= by_id[f"j{i}"].iterations
    assert "32x32:auto:xla:mg" in stats["breakers"]
    assert "32x32:auto:xla" in stats["breakers"]


def test_serve_submit_validates_mg_grid_loudly():
    from poisson_tpu.serve import (
        ServicePolicy,
        SolveRequest,
        SolveService,
    )

    svc = SolveService(ServicePolicy(), seed=0)
    with pytest.raises(ValueError, match="coarsens"):
        svc.submit(SolveRequest(request_id="bad", problem=Problem(M=33, N=33),
                                preconditioner="mg"))
    with pytest.raises(ValueError, match="unknown preconditioner"):
        svc.submit(SolveRequest(request_id="bad2", problem=Problem(M=32, N=32),
                                preconditioner="amg"))
    assert svc.stats()["admitted"] == 0   # rejected, never admitted


def test_serve_policy_default_preconditioner():
    from poisson_tpu.serve import (
        ServicePolicy,
        SolveRequest,
        SolveService,
    )

    p = Problem(M=32, N=32)
    svc = SolveService(ServicePolicy(capacity=8, max_batch=4,
                                     preconditioner="mg"), seed=0)
    svc.submit(SolveRequest(request_id="r0", problem=p))
    outs = svc.drain()
    assert outs[0].converged and outs[0].iterations <= 12
    assert "32x32:auto:xla:mg" in svc.stats()["breakers"]


# -- sentinel: cohort and direction pins --------------------------------


def _rec(value, preconditioner=None):
    detail = {"grid": [400, 600], "dtype": "float32", "platform": "cpu",
              "backend": "xla", "devices": 1}
    if preconditioner is not None:
        detail["preconditioner"] = preconditioner
    return {"metric": "mlups", "value": value, "detail": detail}


def test_sentinel_cohorts_split_by_preconditioner():
    """MG records never judge Jacobi baselines and vice versa: a slow
    MG run beside fast Jacobi history classifies no_baseline (its own
    cohort), never regression against the Jacobi records."""
    import benchmarks.regress as regress

    records = [regress.record_from_result(_rec(500.0), f"jac{i}")
               for i in range(3)]
    records.append(regress.record_from_result(_rec(4.0, "mg"), "mg0"))
    report = regress.evaluate(records)
    verdicts = {v["source"]: v["classification"] for v in report["records"]}
    assert verdicts["mg0"] == "no_baseline"
    assert report["verdict"] == "ok"


def test_sentinel_direction_pin_within_mg_cohort():
    """A genuinely slowed MG run IS caught — inside the MG cohort."""
    import benchmarks.regress as regress

    records = [regress.record_from_result(_rec(4.0, "mg"), f"mg{i}")
               for i in range(3)]
    records.append(regress.record_from_result(_rec(1.0, "mg"), "slow"))
    report = regress.evaluate(records)
    verdicts = {v["source"]: v["classification"] for v in report["records"]}
    assert verdicts["slow"] == "regression"
    assert report["verdict"] == "regression"


def test_bench_ab_detail_shape():
    """The A/B record contract bench.py emits: both arms present, the
    preconditioner in detail (the cohort key), never in the top level."""
    rec = _rec(4.0, "mg")
    rec["detail"]["preconditioner_ab"] = {
        "jacobi": {"iterations": 546}, "mg": {"iterations": 14}}
    import benchmarks.regress as regress

    out = regress.record_from_result(rec, "x")
    assert out["preconditioner"] == "mg"
    # The AB payload is diagnosis, not identity — it must not leak into
    # the cohort key (same rule as the flight-recorder exemplars).
    assert "preconditioner_ab" not in out
    key = regress.cohort_key(out)
    assert "mg" in key


# -- CLI validation (fast failure paths only) ---------------------------


def test_cli_rejects_mg_on_odd_grid():
    import subprocess
    import sys as _sys

    proc = subprocess.run(
        [_sys.executable, "-m", "poisson_tpu", "33", "33",
         "--preconditioner", "mg", "--backend", "xla"],
        capture_output=True, text=True,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode != 0
    assert "coarsens" in proc.stderr


def test_cli_rejects_mg_on_pallas_backend():
    import subprocess
    import sys as _sys

    proc = subprocess.run(
        [_sys.executable, "-m", "poisson_tpu", "64", "64",
         "--preconditioner", "mg", "--backend", "pallas"],
        capture_output=True, text=True,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode != 0
    assert "mg" in proc.stderr


@pytest.mark.slow
def test_mg_selfcheck_cli_smoke():
    import subprocess
    import sys as _sys

    proc = subprocess.run(
        [_sys.executable, "-m", "poisson_tpu.mg.selfcheck"],
        capture_output=True, text=True,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "mg selfcheck OK" in proc.stdout
