"""Communication-avoiding (s=2) CG path tests (ops.pallas_ca).

The 2-sweep fused path is the in-repo reference implementation; these
tests A/B the CA pair-iteration against it in interpret mode — same
system, same convergence criterion, golden counts preserved (the role
the stage-to-stage iteration-count comparison played for the reference,
SURVEY §4.1).
"""

import numpy as np
import pytest

from poisson_tpu.analysis import l2_error_host
from poisson_tpu.config import Problem
from poisson_tpu.ops.pallas_ca import ca_cg_solve
from poisson_tpu.ops.pallas_cg import pallas_cg_solve


@pytest.mark.parametrize("M,N,golden", [(40, 40, 50), (400, 600, 546)])
def test_golden_counts_and_l2(M, N, golden):
    p = Problem(M=M, N=N)
    r = ca_cg_solve(p)
    assert int(r.iterations) == golden
    ref = pallas_cg_solve(p)
    assert abs(l2_error_host(p, r.w) - l2_error_host(p, ref.w)) < 5e-6


def test_solution_matches_two_sweep_path():
    """Same iterate sequence mathematically — solutions agree to fp32
    round-off (the bases differ, so not bitwise)."""
    p = Problem(M=80, N=120)
    r_ca = ca_cg_solve(p)
    r_cg = pallas_cg_solve(p)
    assert int(r_ca.iterations) == int(r_cg.iterations)
    np.testing.assert_allclose(
        np.asarray(r_ca.w), np.asarray(r_cg.w), atol=2e-6
    )


def test_odd_iteration_stop():
    """A grid whose count is odd must stop after the first inner step of
    the final pair — iterations must match the 2-sweep path exactly, not
    round up to even. 56x56 converges in 69 (odd, verified in-suite) so
    the stop1/a2=0 machinery is genuinely exercised — the hardware
    goldens 989/2449 are odd and depend on it."""
    p = Problem(M=56, N=56)
    k_cg = int(pallas_cg_solve(p).iterations)
    assert k_cg % 2 == 1, "grid choice must exercise the odd stop"
    assert int(ca_cg_solve(p).iterations) == k_cg


def test_iteration_cap_respected():
    """The pair loop must truncate to a single inner step at the cap —
    exactly max_iter iterations like the 2-sweep path, never cap+1."""
    for cap in (5, 6):
        p = Problem(M=40, N=40, max_iter=cap)
        r_ca = ca_cg_solve(p)
        r_cg = pallas_cg_solve(p)
        assert int(r_ca.iterations) == cap
        assert int(r_cg.iterations) == cap
        np.testing.assert_allclose(
            np.asarray(r_ca.w), np.asarray(r_cg.w), atol=2e-6
        )


def test_degenerate_rhs_stops_cleanly():
    import jax.numpy as jnp

    from poisson_tpu.ops.pallas_ca import _ca_solve, pick_bm_ca
    from poisson_tpu.ops.pallas_cg import build_canvases

    p = Problem(M=16, N=16, max_iter=5)
    cv, cs, cw, g, rhs, sc2, _ = build_canvases(p, pick_bm_ca(p), "float32", 0)
    s = _ca_solve(p, cv, True, False, False,
                  cs, cw, g, jnp.zeros_like(rhs), sc2)
    assert bool(s.done)
    assert int(s.k) <= 2
    assert np.isfinite(np.asarray(s.x)).all()
    assert (np.asarray(s.x) == 0).all()


def test_serial_reduce_layout_parity():
    p = Problem(M=40, N=40)
    r_def = ca_cg_solve(p, serial=False)
    r_ser = ca_cg_solve(p, serial=True)
    assert int(r_ser.iterations) == int(r_def.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(r_ser.w), np.asarray(r_def.w), rtol=0, atol=5e-6
    )
    with pytest.raises(ValueError, match="parallel"):
        ca_cg_solve(p, serial=True, parallel=True)


def test_gate_is_bit_exact():
    import jax.numpy as jnp

    p = Problem(M=40, N=40)
    r1 = ca_cg_solve(p)
    r2 = ca_cg_solve(p, rhs_gate=jnp.float32(1.0))
    assert int(r1.iterations) == int(r2.iterations)
    assert np.array_equal(np.asarray(r1.w), np.asarray(r2.w))


def test_checkpoint_resume_and_cross_algorithm(tmp_path):
    """CA checkpoints use the shared portable PCGState format: a solve
    interrupted mid-run resumes to the identical result, and a CA
    checkpoint resumes on the 2-sweep fused path (cross-ALGORITHM, the
    strongest portability claim the format makes)."""
    import dataclasses

    from poisson_tpu.ops.pallas_ca import ca_cg_solve_checkpointed
    from poisson_tpu.ops.pallas_cg import pallas_cg_solve_checkpointed

    p = Problem(M=40, N=40)
    one_shot = ca_cg_solve(p)

    # Interrupt at 20 iterations (cap), then resume to convergence.
    ck = str(tmp_path / "ck.npz")
    capped = dataclasses.replace(p, max_iter=20)
    part = ca_cg_solve_checkpointed(capped, ck, chunk=7,
                                    keep_checkpoint=True)
    assert int(part.iterations) == 20
    resumed = ca_cg_solve_checkpointed(p, ck, chunk=7)
    assert int(resumed.iterations) == int(one_shot.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(resumed.w), np.asarray(one_shot.w), atol=2e-6
    )

    # Cross-algorithm: CA checkpoint -> 2-sweep fused resume.
    ck2 = str(tmp_path / "ck2.npz")
    ca_cg_solve_checkpointed(capped, ck2, chunk=7, keep_checkpoint=True)
    crossed = pallas_cg_solve_checkpointed(p, ck2, chunk=7)
    assert int(crossed.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(crossed.w), np.asarray(one_shot.w), atol=2e-6
    )
