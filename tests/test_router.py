"""Roofline observatory & cost-model backend router (tier-1,
CPU-deterministic; -m router).

Four layers under test: the measured-attribution arithmetic
(:mod:`poisson_tpu.obs.roofline` — achieved GB/s against the analytic
bytes/iteration model, per-cohort streaming fraction profiles,
CRC-sealed snapshots), the cold analytic routing table and the
warm-evidence argmin (:mod:`poisson_tpu.serve.router`), the
misprediction sentinel lifecycle (typed event → arm demotion →
cooldown → half-open re-probe → recovery) under an injected
:class:`VirtualClock`, and the byte-compat pins: a router-less service
keeps its historical cohort strings and ``stats()`` shape, and
``executor_backend`` gates every arm through xla so the routed default
path lowers byte-identically (ledger-pinned as
``serve.routed_default_f64``). regress.py cohort-splits on
``routed_backend`` so auto-routed runs never judge fixed baselines,
and the ``top`` scoreboard's Backends pane reads identically from a
live registry snapshot or the Prometheus exposition round trip.
"""

import json
import os
import sys

import pytest

from poisson_tpu.config import Problem
from poisson_tpu.obs import export, forecast, metrics
from poisson_tpu.obs.roofline import (
    DEFAULT_COLD_FRACTION,
    RESIDENT_EFFECTIVE_PASSES,
    RooflineModel,
    effective_passes,
    roofline_cohort,
    snapshot_path,
)
from poisson_tpu.obs.costs import EFFECTIVE_PASSES, grid_points
from poisson_tpu.serve import (
    RouterPolicy,
    ServicePolicy,
    SolveRequest,
    SolveService,
)
from poisson_tpu.serve.router import (
    BACKEND_CA,
    BACKEND_RESIDENT,
    BACKEND_XLA,
    BackendRouter,
    analytic_choice,
    available_backends,
    executor_backend,
    fits_resident_bytes,
)
from poisson_tpu.testing.chaos import VirtualClock

sys.path.insert(0, str(__import__("pathlib").Path(
    __file__).resolve().parents[1]))
from benchmarks import regress  # noqa: E402

pytestmark = pytest.mark.router

P40 = Problem(M=40, N=40)


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    yield
    metrics.reset()


def _observe(model, backend="xla", M=40, N=40, seconds=1e-3,
             iterations=100, **kw):
    return model.observe(backend=backend, M=M, N=N, seconds=seconds,
                         iterations=iterations, **kw)


# -- measured attribution arithmetic -------------------------------------


def test_achieved_fraction_matches_bytes_model(monkeypatch):
    """fraction = passes·points·bytes·iters / seconds / peak — checked
    against a hand computation with a pinned env peak."""
    monkeypatch.setenv("POISSON_TPU_PEAK_GBPS", "100")
    model = RooflineModel()
    s = _observe(model, backend="xla", M=40, N=40, seconds=1e-3,
                 iterations=100, dtype_bytes=8, device_kind="tpu v5e")
    want_bytes = EFFECTIVE_PASSES["xla"] * grid_points(40, 40) * 8 * 100
    want_gbps = want_bytes / 1e-3 / 1e9
    assert s is not None
    assert s.achieved_gbps == pytest.approx(want_gbps, rel=1e-3)
    assert s.peak_gbps == 100.0
    assert s.fraction == pytest.approx(want_gbps / 100.0, rel=1e-3)
    # the first sample is graded against the analytic prior
    assert s.cold and s.expected_fraction == DEFAULT_COLD_FRACTION
    assert metrics.get("obs.roofline.observations") == 1
    assert metrics.get("obs.roofline.cold_cohorts") == 1


def test_unmeasurable_dispatch_is_skipped_not_sampled():
    model = RooflineModel()
    assert _observe(model, seconds=0.0) is None      # VirtualClock
    assert _observe(model, iterations=0) is None
    assert _observe(model, backend="nonesuch") is None  # no model
    assert metrics.get("obs.roofline.skipped") == 3
    assert metrics.get("obs.roofline.observations") == 0


def test_cohort_warms_and_expectation_tracks_p50():
    model = RooflineModel()
    for k in range(5):
        s = _observe(model, seconds=1e-3)
    assert not s.cold and s.samples == 4
    cohort = roofline_cohort("xla", 40, 40, 1, 8, None, 0, None)
    expected, cold, n = model.expected_fraction(cohort)
    assert not cold and n == 5
    # identical dispatches → p50 equals the per-sample fraction and
    # the calibration error collapses to ~0 on repeats
    assert expected == pytest.approx(s.fraction, rel=1e-9)
    assert model.calibration_err_pct() == pytest.approx(0.0, abs=1e-6)


def test_effective_passes_table():
    assert effective_passes("xla") == EFFECTIVE_PASSES["xla"]
    assert effective_passes("pallas_resident") \
        == RESIDENT_EFFECTIVE_PASSES
    assert effective_passes("nonesuch") is None
    # MG adds the V-cycle's fine-equivalent traffic on top
    plain = effective_passes("xla", None, 64, 64, 8)
    mg = effective_passes("xla", "mg", 64, 64, 8)
    assert mg > plain


def test_snapshot_roundtrip_and_torn_audibility(tmp_path):
    model = RooflineModel()
    for _ in range(3):
        _observe(model, seconds=1e-3)
    path = snapshot_path(str(tmp_path / "serve.journal"))
    assert model.save(path)
    loaded = RooflineModel()
    assert loaded.load(path)
    assert loaded.backend_fraction("xla") \
        == model.backend_fraction("xla")
    assert metrics.get("obs.roofline.snapshot.saves") == 1
    assert metrics.get("obs.roofline.snapshot.loads") == 1
    # tear the seal: the torn snapshot is counted and the model stays
    # cold — never trusted
    blob = json.loads(open(path).read())
    blob["crc32"] ^= 1
    open(path, "w").write(json.dumps(blob))
    torn = RooflineModel()
    assert not torn.load(path)
    assert torn.backend_fraction("xla") is None
    assert metrics.get("obs.roofline.snapshot.torn") == 1
    # a missing snapshot is silent (cold start, not an incident)
    fresh = RooflineModel()
    assert not fresh.load(str(tmp_path / "absent.json"))
    assert metrics.get("obs.roofline.snapshot.torn") == 1


# -- the cold analytic routing table -------------------------------------


def test_available_backends_gate_on_device_kind():
    assert available_backends(None) == (BACKEND_XLA,)
    assert available_backends("cpu") == (BACKEND_XLA,)
    assert set(available_backends("TPU v5e")) \
        == {BACKEND_XLA, BACKEND_RESIDENT, BACKEND_CA}
    assert set(available_backends("cpu",
                                  assume=(BACKEND_RESIDENT,))) \
        == {BACKEND_XLA, BACKEND_RESIDENT}


def test_analytic_choice_table():
    arms = (BACKEND_XLA, BACKEND_RESIDENT, BACKEND_CA)
    # VMEM-resident small grid → the resident kernel
    assert fits_resident_bytes(40, 40)
    assert analytic_choice(40, 40, 8, arms) == BACKEND_RESIDENT
    # too big for VMEM, below the CA plateau → xla
    assert not fits_resident_bytes(800, 800)
    assert analytic_choice(800, 800, 8, arms) == BACKEND_XLA
    # on the HBM plateau → communication-avoiding kernel
    assert analytic_choice(4000, 4000, 8, arms) == BACKEND_CA
    # candidates constrain the choice: xla-only part routes xla
    assert analytic_choice(40, 40, 8, (BACKEND_XLA,)) == BACKEND_XLA


def test_executor_gate_pins_every_arm_to_xla():
    """The contract behind the serve.routed_default_f64 ledger pin:
    whatever arm the router names, execution today runs the historical
    xla program — routing changes attribution, never numerics."""
    for arm in (BACKEND_XLA, BACKEND_RESIDENT, BACKEND_CA):
        assert executor_backend(arm) == "xla"


# -- the sentinel lifecycle ----------------------------------------------


def _router(vc, **overrides):
    kw = dict(assume_available=(BACKEND_RESIDENT,),
              misprediction_fraction=0.5, demote_after=1,
              cooldown_seconds=0.05, warm_min_samples=3)
    kw.update(overrides)
    return BackendRouter(RouterPolicy(**kw), RooflineModel(),
                         clock=vc)


def test_misprediction_demotes_then_half_open_recovers():
    vc = VirtualClock()
    router = _router(vc)
    # Cold route on a VMEM-sized grid picks the resident arm
    d1 = router.route(M=40, N=40, dtype_bytes=8)
    assert d1.backend == BACKEND_RESIDENT and d1.cold
    # A slow measured dispatch lands far below the predicted fraction
    vc.advance(1.0)
    slow = router.roofline.observe(
        backend=BACKEND_RESIDENT, M=40, N=40, iterations=50,
        seconds=1.0)
    router.grade(d1, slow)
    assert metrics.get("serve.router.mispredictions") == 1
    assert metrics.get("serve.router.demotions") == 1
    assert router.demoted_arms() == (f"{BACKEND_RESIDENT}:0",)
    # While demoted, traffic downshifts to the xla floor
    d2 = router.route(M=40, N=40, dtype_bytes=8)
    assert d2.backend == BACKEND_XLA
    good2 = router.roofline.observe(
        backend=BACKEND_XLA, M=40, N=40, iterations=50, seconds=5e-5)
    router.grade(d2, good2)
    # Past the cooldown the arm half-opens: one probe, graded against
    # the cold prior, and a healthy measurement recovers it
    vc.advance(0.06)
    d3 = router.route(M=40, N=40, dtype_bytes=8)
    assert d3.backend == BACKEND_RESIDENT
    assert metrics.get("serve.router.half_opens") == 1
    probe = router.roofline.observe(
        backend=BACKEND_RESIDENT, M=40, N=40, iterations=50,
        seconds=5e-5)
    router.grade(d3, probe)
    assert metrics.get("serve.router.recoveries") == 1
    assert router.demoted_arms() == ()
    st = router.stats()
    assert st["chosen"][BACKEND_RESIDENT] == 2
    assert st["chosen"][BACKEND_XLA] == 1


def test_failed_probe_redemotes_without_counting_twice():
    vc = VirtualClock()
    router = _router(vc)
    d1 = router.route(M=40, N=40, dtype_bytes=8)
    vc.advance(1.0)
    router.grade(d1, router.roofline.observe(
        backend=BACKEND_RESIDENT, M=40, N=40, iterations=50,
        seconds=1.0))
    vc.advance(0.06)
    d2 = router.route(M=40, N=40, dtype_bytes=8)
    assert d2.backend == BACKEND_RESIDENT      # the half-open probe
    vc.advance(1.0)
    router.grade(d2, router.roofline.observe(
        backend=BACKEND_RESIDENT, M=40, N=40, iterations=50,
        seconds=1.0))
    assert metrics.get("serve.router.demotions") == 2
    assert metrics.get("serve.router.recoveries") == 0
    assert router.demoted_arms() == (f"{BACKEND_RESIDENT}:0",)


def test_warm_evidence_argmin_prefers_measured_fast_arm():
    vc = VirtualClock()
    router = _router(vc, warm_min_samples=2)
    # Warm the xla cohort with healthy evidence
    for _ in range(3):
        router.roofline.observe(backend=BACKEND_XLA, M=800, N=800,
                                iterations=50, seconds=5e-3)
    d = router.route(M=800, N=800, dtype_bytes=8)
    # 800×800 doesn't fit VMEM; warm xla evidence seals the choice
    assert d.backend == BACKEND_XLA and not d.cold
    assert metrics.get("serve.router.warm_decisions") == 1


def test_backend_downshift_rung_forces_the_floor():
    vc = VirtualClock()
    router = _router(vc, downshift_at=0.5)
    d = router.route(M=40, N=40, dtype_bytes=8, queue_fraction=0.9)
    assert d.backend == BACKEND_XLA and d.forced_xla
    assert metrics.get("serve.degraded.backend_downshift") == 1
    calm = router.route(M=40, N=40, dtype_bytes=8, queue_fraction=0.1)
    assert calm.backend == BACKEND_RESIDENT and not calm.forced_xla


def test_xla_floor_arm_never_demotes():
    vc = VirtualClock()
    router = _router(vc, assume_available=())
    for _ in range(4):
        d = router.route(M=40, N=40, dtype_bytes=8)
        assert d.backend == BACKEND_XLA
        vc.advance(1.0)
        router.grade(d, router.roofline.observe(
            backend=BACKEND_XLA, M=40, N=40, iterations=50,
            seconds=1.0))
    # only the FIRST slow dispatch mispredicts (graded against the
    # cold prior); after that the cohort's expectation has absorbed
    # reality, so a consistently-slow part stops alarming — and the
    # floor arm never demotes regardless
    assert metrics.get("serve.router.mispredictions") == 1
    assert metrics.get("serve.router.demotions") == 0
    assert router.demoted_arms() == ()


def test_fixed_backend_policy_short_circuits():
    vc = VirtualClock()
    router = _router(vc, backend=BACKEND_XLA)
    d = router.route(M=40, N=40, dtype_bytes=8)
    assert d.backend == BACKEND_XLA
    # a fixed arm the part doesn't offer falls back to the floor
    router2 = _router(vc, backend=BACKEND_CA, assume_available=())
    assert router2.route(M=40, N=40, dtype_bytes=8).backend \
        == BACKEND_XLA


# -- the service seam ----------------------------------------------------


def test_router_off_by_default_byte_compat():
    """ServicePolicy().router is None, the historical cohort string is
    unchanged, stats() has no router block, and no router counters
    tick — the default path is indistinguishable from PR 18."""
    assert ServicePolicy().router is None
    svc = SolveService()
    svc.submit(SolveRequest(request_id=0, problem=P40))
    assert svc._cohort(svc._queue[0].request) == "40x40:auto:xla"
    outs = svc.drain()
    assert all(o.converged for o in outs)
    st = svc.stats()
    assert "router" not in st and st["lost"] == 0
    assert metrics.get("serve.router.decisions") == 0


def test_routed_service_splits_cohort_and_serves_all():
    vc = VirtualClock()
    svc = SolveService(
        ServicePolicy(capacity=16, router=RouterPolicy(
            assume_available=(BACKEND_RESIDENT,))),
        clock=vc, sleep=vc.sleep, seed=0)
    svc.submit(SolveRequest(request_id=0, problem=P40))
    # the routed arm is IN the breaker cohort: a melting-down routed
    # backend trips its own breaker, not the xla floor's
    assert svc._cohort(svc._queue[0].request) \
        == f"40x40:auto:{BACKEND_RESIDENT}"
    outs = svc.drain()
    assert all(o.converged for o in outs)
    st = svc.stats()
    assert st["lost"] == 0
    assert st["router"]["decisions"] == 1
    assert st["router"]["chosen"] == {BACKEND_RESIDENT: 1}


def test_routed_mixed_run_spans_backends_zero_lost():
    """The acceptance shape: a router-on run under an injected slow
    backend draws misprediction + demotion + recovery, spans ≥2
    distinct backends, and loses nothing (the chaos scenario asserts
    the same end to end; this is the in-suite pin)."""
    from poisson_tpu.testing import chaos

    report = chaos.run_scenario("router-mispredict-downshift", seed=0)
    assert report["ok"], report
    assert report["checks"]["traffic_spanned_backends"]
    assert report["checks"]["healthy_probe_recovered"]
    assert report["checks"]["no_lost_requests"]


def test_journal_snapshot_warm_loads_on_recover(tmp_path):
    from poisson_tpu.serve import SolveJournal

    jpath = str(tmp_path / "serve.journal")
    vc0 = VirtualClock()
    svc = SolveService(ServicePolicy(capacity=16),
                       clock=vc0, sleep=vc0.sleep,
                       journal=SolveJournal(jpath, clock=vc0),
                       dispatch_fault=lambda reqs, att: vc0.advance(
                           1e-3))
    for i in range(3):
        svc.submit(SolveRequest(request_id=f"w{i}", problem=P40))
    svc.drain()
    assert os.path.exists(snapshot_path(jpath))
    vc = VirtualClock()
    revived = SolveService.recover(SolveJournal(jpath, clock=vc),
                                   ServicePolicy(capacity=16),
                                   clock=vc, sleep=vc.sleep)
    assert revived._roofline.backend_fraction("xla") is not None


# -- regress cohort split ------------------------------------------------


def _serve_record(value, routed):
    det = {"grid": [40, 40], "dtype": "float32", "platform": "cpu",
           "backend": "xla_serve", "devices": 1,
           "fault_load": "clean"}
    if routed is not None:
        det["routed_backend"] = routed
    return regress.record_from_result(
        {"metric": "serve.sustained_solves_per_sec", "value": value,
         "detail": det}, "r")


def test_regress_routed_backend_splits_the_cohort():
    auto = _serve_record(1.0, "auto")
    off = _serve_record(5.0, "off")
    legacy = _serve_record(5.0, None)
    assert auto["routed_backend"] == "auto"
    assert regress.cohort_key(auto) != regress.cohort_key(off)
    # pre-router artifacts normalize to the "off" cohort — history
    # stays comparable
    assert regress.cohort_key(legacy) == regress.cohort_key(off)
    # an auto-routed run never judges the fixed baseline: a 5x gap
    # across the split raises no alarm, and the direction pin still
    # fires within a cohort
    assert not regress.evaluate([off, off, off, auto])["regressions"]
    slow = _serve_record(1.0, "off")
    verdict = regress.evaluate([off, off, off, slow])
    assert verdict["regressions"]


# -- the scoreboard ------------------------------------------------------


def test_scoreboard_backends_pane_agrees_across_sources():
    vc = VirtualClock()
    router = _router(vc)
    d = router.route(M=40, N=40, dtype_bytes=8)
    vc.advance(1.0)
    router.grade(d, router.roofline.observe(
        backend=BACKEND_RESIDENT, M=40, N=40, iterations=50,
        seconds=1.0))
    router.route(M=40, N=40, dtype_bytes=8)
    snap = metrics.snapshot()
    live = forecast.build_scoreboard(snap)
    wire = forecast.build_scoreboard(export.parse_text(
        export.render(snap)))
    assert live["backends"] == wire["backends"]
    assert live["backends"]["decisions"] == 2
    assert live["backends"]["mispredictions"] == 1
    assert live["backends"]["chosen"]
    text = forecast.render_scoreboard(live)
    assert "backends" in text and "mispred" in text
    # pre-router snapshots still render (dark pane, no crash)
    old = dict(live)
    old.pop("backends", None)
    assert forecast.render_scoreboard(old)
