"""Operator-layer tests: stencil symmetry/positivity, preconditioner, dot."""

import jax.numpy as jnp
import numpy as np

from poisson_tpu.config import Problem
from poisson_tpu.models.fictitious_domain import build_fields
from poisson_tpu.ops.stencil import (
    apply_A,
    apply_Dinv,
    diag_D,
    dot_weighted,
    pad_interior,
)


def _random_field(p, seed):
    rng = np.random.default_rng(seed)
    return pad_interior(jnp.asarray(rng.standard_normal(p.interior_shape)))


def test_apply_A_is_symmetric():
    p = Problem(M=24, N=18)
    a, b, _ = build_fields(p)
    u, v = _random_field(p, 1), _random_field(p, 2)
    Au = apply_A(u, a, b, p.h1, p.h2)
    Av = apply_A(v, a, b, p.h1, p.h2)
    lhs = float(dot_weighted(Au, v, p.h1, p.h2))
    rhs = float(dot_weighted(u, Av, p.h1, p.h2))
    np.testing.assert_allclose(lhs, rhs, rtol=1e-12)


def test_apply_A_is_positive_definite():
    p = Problem(M=24, N=18)
    a, b, _ = build_fields(p)
    for seed in range(5):
        u = _random_field(p, seed)
        assert float(dot_weighted(apply_A(u, a, b, p.h1, p.h2), u, p.h1, p.h2)) > 0


def test_apply_A_matches_pointwise_formula():
    p = Problem(M=9, N=7)
    a, b, _ = build_fields(p)
    w = _random_field(p, 3)
    Aw = np.asarray(apply_A(w, a, b, p.h1, p.h2))
    a_, b_, w_ = np.asarray(a), np.asarray(b), np.asarray(w)
    h1, h2 = p.h1, p.h2
    for i in range(1, p.M):
        for j in range(1, p.N):
            ax = -(
                a_[i + 1, j] * (w_[i + 1, j] - w_[i, j])
                - a_[i, j] * (w_[i, j] - w_[i - 1, j])
            ) / (h1 * h1)
            ay = -(
                b_[i, j + 1] * (w_[i, j + 1] - w_[i, j])
                - b_[i, j] * (w_[i, j] - w_[i, j - 1])
            ) / (h2 * h2)
            np.testing.assert_allclose(Aw[i, j], ax + ay, rtol=1e-12)
    # Dirichlet ring untouched.
    assert Aw[0, :].any() == False  # noqa: E712
    assert Aw[-1, :].any() == False  # noqa: E712


def test_apply_Dinv_matches_direct_division():
    p = Problem(M=12, N=10)
    a, b, _ = build_fields(p)
    d = diag_D(a, b, p.h1, p.h2)
    r = _random_field(p, 4)
    z = np.asarray(apply_Dinv(r, d))
    d_, r_ = np.asarray(d), np.asarray(r)
    # XLA:CPU lowers f64 division via reciprocal refinement (~1e-14 rel).
    np.testing.assert_allclose(z[1:-1, 1:-1], r_[1:-1, 1:-1] / d_, rtol=1e-13)


def test_dot_weighted_excludes_boundary():
    p = Problem(M=6, N=6)
    u = jnp.ones(p.grid_shape)
    import pytest

    got = float(dot_weighted(u, u, p.h1, p.h2))
    assert got == pytest.approx((p.M - 1) * (p.N - 1) * p.h1 * p.h2, rel=1e-14)
