"""Tenant isolation & overload fairness (tier-1, CPU-deterministic;
-m tenancy).

Four layers under test: the pure-Python ledger arithmetic
(:mod:`poisson_tpu.serve.tenancy` — token-bucket quota refill under a
:class:`VirtualClock`, smooth weighted-round-robin deficit counters,
retry budgets with success refunds and crash re-charge), the service
seam (over-quota submits shed typed ``quota_exceeded`` at zero
compute, the dispatch-head mix converges to the share vector under
both engines, budget exhaustion converts a requeue into a typed
error), durability (tenant identity and spent retry budgets survive a
journal replay — a poisoned tenant cannot launder its amplification
cap by crashing the process), and the byte-compat pin: a tenancy-less
service keeps its historical cohort strings, ``stats()`` shape, and
silent counters, with ``SolveRequest.tenant`` inert metadata.
regress.py cohort-splits on ``tenant_mix`` so a fair-queued
multi-tenant run never judges a single-tenant FIFO baseline, and the
chaos scenarios (``tenant-noisy-neighbor``, ``tenant-retry-storm``)
are pinned in-suite.
"""

import os
import sys

import pytest

from poisson_tpu.config import Problem
from poisson_tpu.obs import metrics
from poisson_tpu.serve import (
    BreakerPolicy,
    DegradationPolicy,
    OUTCOME_ERROR,
    OUTCOME_RESULT,
    OUTCOME_SHED,
    RetryPolicy,
    SHED_QUOTA_EXCEEDED,
    ServicePolicy,
    SolveJournal,
    SolveRequest,
    SolveService,
    TenancyPolicy,
    TransientDispatchError,
    parse_tenant_spec,
)
from poisson_tpu.serve.tenancy import DEFAULT_TENANT, TenantLedger
from poisson_tpu.testing.chaos import VirtualClock

sys.path.insert(0, str(__import__("pathlib").Path(
    __file__).resolve().parents[1]))
from benchmarks import regress  # noqa: E402

pytestmark = pytest.mark.tenancy

P40 = Problem(M=40, N=40)


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    yield
    metrics.reset()


def _quiet_degradation():
    return DegradationPolicy(shrink_padding_at=9.0, cap_iterations_at=9.0,
                             downshift_precision_at=9.0)


def _service(policy, **kw):
    vc = VirtualClock()
    svc = SolveService(policy, clock=vc, sleep=vc.sleep, **kw)
    return svc, vc


# -- the ledger arithmetic -----------------------------------------------


def test_quota_bucket_refill_and_burst_cap():
    vc = VirtualClock()
    ledger = TenantLedger(
        TenancyPolicy(shares=(("b", 2.0),), quota_rate=1.0,
                      quota_burst=2.0),
        clock=vc)
    # buckets start full: burst × share tokens
    assert ledger.state("b").tokens == 4.0
    for _ in range(4):
        assert ledger.admit("b")
    assert not ledger.admit("b")           # dry
    # refill at rate × share: 1 s buys 2 tokens for share-2 tenant b
    vc.advance(1.0)
    assert ledger.admit("b") and ledger.admit("b")
    assert not ledger.admit("b")
    # refill caps at burst × share — idling forever buys one burst, not
    # an unbounded backlog of tokens
    vc.advance(1e6)
    ledger.admit("b")
    assert ledger.state("b").tokens == pytest.approx(3.0)
    # unnamed tenants run at default_share; quota_rate=0 would disable
    # the quota entirely (covered by the default-off service pin)
    assert ledger.share_of("anon") == 1.0
    assert ledger.resolve(None) == DEFAULT_TENANT


def test_dwrr_pick_converges_to_share_vector():
    vc = VirtualClock()
    ledger = TenantLedger(TenancyPolicy(shares=(("a", 1.0), ("b", 3.0))),
                          clock=vc)
    picks = [ledger.pick(("a", "b")) for _ in range(400)]
    assert picks.count("b") == 300 and picks.count("a") == 100
    # work-conserving: a lone backlogged tenant always wins
    assert ledger.pick(("a",)) == "a"


def test_retry_budget_spend_refund_and_crash_recharge():
    vc = VirtualClock()
    ledger = TenantLedger(TenancyPolicy(retry_budget=2), clock=vc)
    assert ledger.spend_retry("p") and ledger.spend_retry("p")
    assert not ledger.spend_retry("p")     # exhausted
    # only completions replenish, capped at the budget
    ledger.credit_success("p")
    assert ledger.spend_retry("p")
    for _ in range(9):
        ledger.credit_success("p")
    assert ledger.state("p").retry_tokens == 2.0
    # journal replay re-charges journaled attempts, floored at zero
    ledger.charge_attempts("p", 99)
    assert ledger.state("p").retry_tokens == 0.0
    assert not ledger.spend_retry("p")


def test_ledger_rejects_bad_policy():
    vc = VirtualClock()
    for bad in (TenancyPolicy(default_share=0.0),
                TenancyPolicy(quota_rate=-1.0),
                TenancyPolicy(quota_burst=0.0),
                TenancyPolicy(retry_budget=-1),
                TenancyPolicy(shares=(("a", 0.0),))):
        with pytest.raises(ValueError):
            TenantLedger(bad, clock=vc)


# -- CLI spec parsing ----------------------------------------------------


def test_parse_tenant_spec_accepts_weights_and_bare_names():
    assert parse_tenant_spec("a:1,b:4") == (("a", 1.0), ("b", 4.0))
    # a bare name is share 1.0; whitespace is cosmetic
    assert parse_tenant_spec(" a , b:2.5 ") == (("a", 1.0), ("b", 2.5))


def test_parse_tenant_spec_loud_on_garbage():
    for spec, fragment in (("", "empty"),
                           ("a:1,,b:2", "empty tenant entry"),
                           (":3", "name missing"),
                           ("a:x", "non-numeric"),
                           ("a:0", "non-positive"),
                           ("a:-1", "non-positive"),
                           ("a:1,a:2", "duplicate")):
        with pytest.raises(ValueError, match=fragment):
            parse_tenant_spec(spec)


# -- default-off byte-compat --------------------------------------------


def test_tenancy_off_by_default_byte_compat():
    """ServicePolicy().tenancy is None, the historical cohort string is
    unchanged, stats() has no tenants block, no serve.tenant.* counter
    ticks, and a tenant= tag on the request is inert metadata — the
    default path is indistinguishable from PR 19."""
    assert ServicePolicy().tenancy is None
    svc = SolveService()
    svc.submit(SolveRequest(request_id=0, problem=P40, tenant="loud"))
    assert svc._cohort(svc._queue[0].request) == "40x40:auto:xla"
    outs = svc.drain()
    assert all(o.converged for o in outs)
    st = svc.stats()
    assert "tenants" not in st and st["lost"] == 0
    assert metrics.get("serve.tenant.promotions") == 0
    assert metrics.get("serve.tenant.admitted.loud") == 0
    assert metrics.get("serve.tenant.quota_sheds") == 0


# -- the service seam: quota sheds ---------------------------------------


def test_over_quota_submit_sheds_typed_at_zero_compute():
    svc, _ = _service(ServicePolicy(
        capacity=16,
        tenancy=TenancyPolicy(quota_rate=1e-3, quota_burst=1.0)))
    assert svc.submit(SolveRequest(request_id="h0", problem=P40,
                                   tenant="hog")) is None
    shed = svc.submit(SolveRequest(request_id="h1", problem=P40,
                                   tenant="hog"))
    assert shed is not None and shed.kind == OUTCOME_SHED
    assert shed.shed_reason == SHED_QUOTA_EXCEEDED
    assert "hog" in shed.message and "quota" in shed.message
    # the shed burned zero compute: no dispatch, no solve seconds
    dec = shed.decomposition or {}
    assert dec.get("compute_s", 1) == 0
    assert dec.get("dispatches", 1) == 0
    # another tenant's bucket is untouched by hog's exhaustion
    assert svc.submit(SolveRequest(request_id="q0", problem=P40,
                                   tenant="quiet")) is None
    svc.drain()
    st = svc.stats()
    # ledger invariant closes through the same _shed path as queue_full
    assert st["admitted"] == 3 and st["shed"] == 1 and st["lost"] == 0
    assert metrics.get("serve.tenant.quota_sheds") == 1
    assert metrics.get(f"serve.shed.{SHED_QUOTA_EXCEEDED}") == 1
    assert metrics.get("serve.tenant.admitted.hog") == 2
    assert metrics.get("serve.tenant.shed.hog") == 1
    assert metrics.get("serve.tenant.completed.quiet") == 1


# -- the service seam: weighted-fair draining ----------------------------


def _dispatch_order(policy):
    """Drain 4 a-requests submitted ahead of 4 b-requests and return
    the tenant of each dispatch head in order."""
    order = []

    def spy(requests, attempts):
        order.extend(r.tenant for r in requests)

    svc, _ = _service(policy, dispatch_fault=spy)
    for i in range(4):
        svc.submit(SolveRequest(request_id=f"a{i}", problem=P40,
                                tenant="a"))
    for i in range(4):
        svc.submit(SolveRequest(request_id=f"b{i}", problem=P40,
                                tenant="b"))
    outs = svc.drain()
    assert len(outs) == 8 and all(o.kind == OUTCOME_RESULT for o in outs)
    assert svc.stats()["lost"] == 0
    return order


def test_dwrr_reorders_dispatch_heads_by_share_drain_engine():
    order = _dispatch_order(ServicePolicy(
        capacity=16, max_batch=1,
        tenancy=TenancyPolicy(shares=(("a", 1.0), ("b", 3.0)))))
    # every a arrived before every b, yet the first scheduling window
    # serves b 3:1 — shares reorder across tenants (FIFO within one)
    assert order[:4].count("b") == 3
    assert [r for r in order if r == "a"] == ["a"] * 4
    assert metrics.get("serve.tenant.promotions") >= 1
    assert metrics.get("serve.tenant.dispatches.b") == 4


def test_dwrr_reorders_dispatch_heads_continuous_engine():
    from poisson_tpu.serve import SCHED_CONTINUOUS

    order = _dispatch_order(ServicePolicy(
        capacity=16, max_batch=1, scheduling=SCHED_CONTINUOUS,
        tenancy=TenancyPolicy(shares=(("a", 1.0), ("b", 3.0)))))
    # same fairness contract under the continuous-refill engine: the
    # late-arriving heavy tenant overtakes the FIFO backlog
    assert order[:4].count("b") >= 2
    assert sorted(set(order)) == ["a", "b"]
    assert metrics.get("serve.tenant.promotions") >= 1


# -- the service seam: retry budgets -------------------------------------


def test_retry_budget_exhaustion_converts_requeue_to_typed_error():
    budget = 2

    def poison(requests, attempts):
        if any(str(r.request_id).startswith("p") for r in requests):
            raise TransientDispatchError("injected outage")

    svc, _ = _service(
        ServicePolicy(
            capacity=16, max_batch=1,
            retry=RetryPolicy(max_attempts=50, backoff_base=0.01,
                              backoff_cap=0.05),
            # the breaker must not shed the poisoned cohort first — this
            # test isolates the budget rail
            breaker=BreakerPolicy(failure_threshold=10**6),
            degradation=_quiet_degradation(),
            tenancy=TenancyPolicy(retry_budget=budget)),
        dispatch_fault=poison)
    svc.submit(SolveRequest(request_id="p0", problem=P40, tenant="poison"))
    svc.submit(SolveRequest(request_id="s0", problem=P40, tenant="steady"))
    outs = {o.request_id: o for o in svc.drain()}
    # amplification cap: 1 admission + budget requeues, then typed error
    assert metrics.get("serve.tenant.dispatches.poison") == 1 + budget
    bad = outs["p0"]
    assert bad.kind == OUTCOME_ERROR
    assert "retry budget exhausted" in bad.message
    assert metrics.get("serve.tenant.retry_exhausted") == 1
    assert metrics.get("serve.tenant.retries.poison") == budget
    # the steady tenant is untouched: converged, budget never spent
    assert outs["s0"].kind == OUTCOME_RESULT and outs["s0"].converged
    assert metrics.get("serve.tenant.retries.steady") == 0
    assert svc.stats()["lost"] == 0


# -- durability: the journal replay boundary -----------------------------


def test_tenant_and_spent_budget_survive_journal_recover(tmp_path):
    budget = 3
    jpath = str(tmp_path / "serve.journal")
    tenancy = TenancyPolicy(retry_budget=budget)
    vc0 = VirtualClock()

    def poison(requests, attempts):
        vc0.advance(1e-3)
        if any(str(r.request_id).startswith("p") for r in requests):
            raise TransientDispatchError("injected outage")

    svc = SolveService(
        ServicePolicy(capacity=16, max_batch=1,
                      retry=RetryPolicy(max_attempts=50,
                                        backoff_base=0.01,
                                        backoff_cap=0.05),
                      breaker=BreakerPolicy(failure_threshold=10**6),
                      degradation=_quiet_degradation(),
                      tenancy=tenancy),
        clock=vc0, sleep=vc0.sleep,
        journal=SolveJournal(jpath, clock=vc0),
        dispatch_fault=poison)
    svc.submit(SolveRequest(request_id="p0", problem=P40, tenant="poison"))
    svc.submit(SolveRequest(request_id="s0", problem=P40, tenant="steady"))
    # pump mid-storm (few enough rounds that the budget is spent but
    # not yet exhausted), then "crash" (abandon without draining)
    for _ in range(3):
        svc.pump()
    attempts = metrics.get("serve.tenant.dispatches.poison")
    assert attempts >= 2
    assert os.path.exists(jpath)

    metrics.reset()
    vc = VirtualClock()
    revived = SolveService.recover(
        SolveJournal(jpath, clock=vc),
        ServicePolicy(capacity=16, max_batch=1,
                      retry=RetryPolicy(max_attempts=50,
                                        backoff_base=0.01,
                                        backoff_cap=0.05),
                      breaker=BreakerPolicy(failure_threshold=10**6),
                      degradation=_quiet_degradation(),
                      tenancy=tenancy),
        clock=vc, sleep=vc.sleep)
    # tenant identity rode the journal: the recovered entry knows who
    # it belongs to (s0 completed before the crash — its outcome was
    # replayed, not re-enqueued), and the poisoned tenant's journaled
    # attempts beyond the first were re-charged — crashing mid-storm
    # does not reset the amplification cap
    pend = {str(e.request.request_id): e.request.tenant
            for e in list(revived._queue) + revived._delayed}
    assert pend == {"p0": "poison"}
    assert revived._tenancy.state("poison").retry_tokens \
        == max(0.0, budget - (attempts - 1))
    # the fault died with the old process: the revived service drains
    # clean and attributes the completion to its tenant
    outs = revived.drain()
    assert [str(o.request_id) for o in outs] == ["p0"]
    assert outs[0].kind == OUTCOME_RESULT and outs[0].converged
    assert metrics.get("serve.tenant.completed.poison") == 1
    assert revived.stats()["lost"] == 0


# -- per-tenant SLO burn & the stats surface -----------------------------


def test_per_tenant_slo_burn_and_stats_block():
    svc, _ = _service(ServicePolicy(
        capacity=16, tenancy=TenancyPolicy(shares=(("a", 2.0),))))
    svc.submit(SolveRequest(request_id="a0", problem=P40, tenant="a"))
    svc.submit(SolveRequest(request_id="b0", problem=P40, tenant="b"))
    svc.drain()
    # one SLO surface per tenant, prefixed so the global serve.slo.*
    # counters stay exactly the fleet-wide totals (no double counting)
    assert metrics.get("serve.tenant.slo.a.good") == 1
    assert metrics.get("serve.tenant.slo.b.good") == 1
    assert metrics.get("serve.slo.good") == 2
    snap = metrics.snapshot()
    gauges = snap.get("gauges", snap)
    assert "serve.tenant.share.a" in str(sorted(gauges))
    st = svc.stats()["tenants"]
    assert st["a"]["share"] == 2.0 and st["b"]["share"] == 1.0
    assert st["a"]["slo_budget_remaining"] <= 1.0
    # retry budgeting on by default: tokens visible, full
    assert st["a"]["retry_tokens"] == float(
        TenancyPolicy().retry_budget)


# -- regress cohort split ------------------------------------------------


def _serve_record(value, mix):
    det = {"grid": [40, 40], "dtype": "float32", "platform": "cpu",
           "backend": "xla_serve", "devices": 1,
           "fault_load": "clean"}
    if mix is not None:
        det["tenant_mix"] = mix
    return regress.record_from_result(
        {"metric": "serve.sustained_solves_per_sec", "value": value,
         "detail": det}, "r")


def test_regress_tenant_mix_splits_the_cohort():
    mixed = _serve_record(1.0, "a:1,b:4")
    off = _serve_record(5.0, "off")
    legacy = _serve_record(5.0, None)
    assert mixed["tenant_mix"] == "a:1,b:4"
    assert regress.cohort_key(mixed) != regress.cohort_key(off)
    # pre-tenancy artifacts normalize to the "off" cohort — history
    # stays comparable
    assert regress.cohort_key(legacy) == regress.cohort_key(off)
    # a fair-queued mixed-tenant run never judges the single-tenant
    # FIFO baseline: a 5x gap across the split raises no alarm, and
    # the direction pin still fires within a cohort
    assert not regress.evaluate([off, off, off, mixed])["regressions"]
    slow = _serve_record(1.0, "off")
    assert regress.evaluate([off, off, off, slow])["regressions"]


# -- chaos pins ----------------------------------------------------------


def test_noisy_neighbor_chaos_isolates_the_victim():
    """The acceptance shape: under a 10x aggressor flood the victim's
    completed count and p99 hold within 10% of its solo baseline with
    tenancy on, starvation is demonstrated with tenancy off, and the
    aggressor's overflow sheds typed at zero compute (the chaos
    scenario asserts the same end to end; this is the in-suite pin)."""
    from poisson_tpu.testing import chaos

    report = chaos.run_scenario("tenant-noisy-neighbor", seed=0)
    assert report["ok"], report
    assert report["checks"]["off_arm_starves_victim"]
    assert report["checks"]["on_arm_victim_all_served"]
    assert report["checks"]["on_arm_victim_p99_within_10pct"]
    assert report["checks"]["quota_sheds_burned_zero_compute"]
    assert report["checks"]["no_lost_requests"]


def test_retry_storm_chaos_caps_amplification():
    from poisson_tpu.testing import chaos

    report = chaos.run_scenario("tenant-retry-storm", seed=0)
    assert report["ok"], report
    assert report["checks"]["requeue_amplification_capped"]
    assert report["checks"]["budget_exhaustion_typed"]
    assert report["checks"]["steady_tenant_untouched"]
    assert report["checks"]["no_lost_requests"]
