"""Unified telemetry (`poisson_tpu.obs`): spans, counters, streaming.

The acceptance surface of the observability subsystem:

- emitted trace files load as valid Chrome trace JSON (required
  ``ph``/``ts``/``name`` keys) and open-in-Perfetto structure;
- counters record the expected restart/escalation counts under fault
  injection (``testing.faults``), and the resilient driver surfaces its
  recovery history on SUCCESS, not only inside ``DivergenceError``;
- a CPU-mesh sharded solve produces mergeable per-rank event logs;
- streaming enabled vs disabled leaves iteration counts identical (the
  golden-count guarantee is structural: ``stream_every`` is a static
  compile flag);
- the CLI acceptance command wires the whole stack end to end.
"""

from __future__ import annotations

import json
import warnings

import numpy as np
import pytest

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.obs import metrics, stream
from poisson_tpu.obs.trace import TraceRecorder, load_events, merge_trace_dir
from poisson_tpu.solvers.pcg import FLAG_CONVERGED, pcg_solve
from poisson_tpu.solvers.resilient import RecoveryPolicy, pcg_solve_resilient
from poisson_tpu.testing.faults import FaultPlan, chunk_hook, inject_nan

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Telemetry state is process-global; each test starts and ends
    clean so order cannot leak counters or recorders across tests."""
    obs.shutdown()
    metrics.reset()
    yield
    obs.shutdown()
    metrics.reset()


def _load_trace(path) -> list[dict]:
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert events, f"no traceEvents in {path}"
    for ev in events:
        for key in ("ph", "ts", "name"):
            assert key in ev, f"trace event missing {key!r}: {ev}"
    return events


# ---------------------------------------------------------------------------
# Spans / trace files
# ---------------------------------------------------------------------------


def test_trace_file_is_valid_chrome_trace(tmp_path):
    rec = obs.configure(trace_dir=str(tmp_path))
    with obs.span("outer", grid="40x40"):
        with obs.span("inner", fence=False):
            pass
    obs.event("marker", k=7)
    obs.finalize()
    events = _load_trace(rec.trace_path)
    by_name = {ev["name"]: ev for ev in events}
    assert {"outer", "inner", "marker"} <= set(by_name)
    # Spans are complete events with real durations; nesting is recorded
    # in wall time (inner inside outer).
    assert by_name["outer"]["ph"] == "X" and by_name["outer"]["dur"] >= 0
    assert by_name["marker"]["ph"] == "i"
    assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]
    # Every event is attributed to this process's rank.
    assert {ev["pid"] for ev in events} == {rec.rank}


def test_event_log_schema_and_span_nesting(tmp_path):
    obs.configure(trace_dir=str(tmp_path))
    with obs.span("phase"):
        with obs.span("step", fence=False):
            obs.event("tick", k=1)
    obs.finalize()
    records = load_events(str(tmp_path))
    assert [r["name"] for r in records] == [
        "phase", "step", "tick", "step", "phase"
    ]
    for r in records:
        for key in ("at_unix", "at_mono", "rank", "kind", "name"):
            assert key in r
    step_end = [r for r in records
                if r["kind"] == "span_end" and r["name"] == "step"][0]
    assert step_end["span_path"] == "phase/step"
    assert step_end["seconds"] >= 0


def test_unconfigured_telemetry_is_a_noop():
    """Call sites never guard: spans/events with no recorder must work
    (and record nothing)."""
    assert obs.recorder() is None
    with obs.span("anything"):
        obs.event("nothing", a=1)
    assert obs.recent_events() == []
    obs.finalize()  # idempotent with no configuration


# ---------------------------------------------------------------------------
# Counters under fault injection
# ---------------------------------------------------------------------------


def test_restart_counters_match_injected_fault():
    p = Problem(M=40, N=40)
    hook = chunk_hook(FaultPlan(nan_at_iteration=15))
    with pytest.warns(RuntimeWarning, match="nonfinite.*restart"):
        res = pcg_solve_resilient(p, chunk=10, on_chunk=hook)
    assert int(res.flag) == FLAG_CONVERGED
    assert metrics.get("resilient.restarts") == 1
    assert metrics.get("resilient.escalations") == 0
    # Recovery history is surfaced on SUCCESS too (satellite: it used to
    # exist only inside DivergenceError).
    assert res.restarts == 1
    assert len(res.recovery_history) == 1
    k, verdict, action = res.recovery_history[0]
    assert verdict == "nonfinite" and action.startswith("restart@")


def test_escalation_counter_counts_the_ladder():
    p = Problem(M=40, N=40)
    count = {"n": 0}

    def hook(state, chunks_done):
        if count["n"] < 2 and int(state.k) >= 10:
            count["n"] += 1
            return inject_nan(state)
        return None

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        res = pcg_solve_resilient(p, dtype="float32", chunk=10,
                                  on_chunk=hook)
    assert int(res.flag) == FLAG_CONVERGED
    assert metrics.get("resilient.restarts") == 2
    assert metrics.get("resilient.escalations") == 1
    assert res.restarts == 2
    assert any("escalate->" in action
               for _, _, action in res.recovery_history)


def test_clean_solve_reports_no_recovery():
    p = Problem(M=40, N=40)
    res = pcg_solve_resilient(p, chunk=10,
                              policy=RecoveryPolicy(stagnation_window=200))
    assert res.restarts == 0 and res.recovery_history == ()
    assert metrics.get("resilient.restarts") == 0


def test_checkpoint_counters(tmp_path):
    from poisson_tpu.solvers import checkpoint as ckpt
    from poisson_tpu.testing.faults import corrupt_file

    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    ckpt.pcg_solve_checkpointed(p, path, chunk=10, keep_checkpoint=True)
    writes = metrics.get("checkpoint.writes")
    assert writes >= 4          # 50 iterations / chunk 10
    # Corrupt the newest generation: the reload falls back and counts
    # the corruption (a flipped byte lands either in array payload —
    # CRC catch — or in the zip structure — unreadable) plus the
    # generation fallback.
    corrupt_file(path, "flip")
    fp = ckpt._fingerprint(p, "float64", False)
    with pytest.warns(RuntimeWarning):
        state = ckpt.load_state(path, fp)
    assert state is not None    # fell back to ck.npz.1
    assert (metrics.get("checkpoint.crc_failures")
            + metrics.get("checkpoint.corrupt")) == 1
    assert metrics.get("checkpoint.generation_fallbacks") == 1


def test_crc_failure_counter_on_payload_flip(tmp_path):
    """A flip confined to array payload passes the zip/npy parsers and
    is caught ONLY by the CRC seal — the counter must say so."""
    import numpy as np_

    from poisson_tpu.solvers import checkpoint as ckpt

    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    ckpt.pcg_solve_checkpointed(p, path, chunk=10, keep_checkpoint=True)
    # Rewrite the newest generation uncompressed-equivalent: flip one
    # byte inside the 'w' array payload specifically.
    with np_.load(path) as data:
        arrays = {k: np_.array(data[k]) for k in data.files}
    w = arrays["w"]
    w.view(np_.uint8).reshape(-1)[w.nbytes // 2] ^= 0xFF
    np_.savez(path, **arrays)       # CRC record kept, payload changed
    fp = ckpt._fingerprint(p, "float64", False)
    with pytest.warns(RuntimeWarning):
        state = ckpt.load_state(path, fp)
    assert state is not None
    assert metrics.get("checkpoint.crc_failures") == 1


# ---------------------------------------------------------------------------
# Sharded solves: mergeable per-rank event logs
# ---------------------------------------------------------------------------


def test_sharded_solve_produces_mergeable_per_rank_logs(tmp_path):
    """A sharded solve records telemetry under its rank; logs written by
    other ranks of a multihost run (simulated here — single-process CPU
    meshes are all rank 0) merge into one timeline."""
    import jax

    from poisson_tpu.parallel import make_solver_mesh
    from poisson_tpu.parallel.checkpoint_sharded import (
        pcg_solve_sharded_checkpointed,
    )

    tdir = str(tmp_path)
    obs.configure(trace_dir=tdir, rank=0)
    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices()[:4], grid=(2, 2))
    with obs.span("sharded_solve"):
        res = pcg_solve_sharded_checkpointed(
            p, mesh, str(tmp_path / "ck.npz"), chunk=10,
        )
    assert int(res.iterations) == 50
    obs.finalize()

    # A second rank's recorder, as another host of the same run would
    # write it (same dir, different rank).
    other = TraceRecorder(trace_dir=tdir, rank=1)
    with other.span("sharded_solve", fence=False):
        other.event("checkpoint.write", k=10)
    other.close()

    records = load_events(tdir)
    assert {r["rank"] for r in records} == {0, 1}
    assert [r["at_unix"] for r in records] == sorted(
        r["at_unix"] for r in records
    )
    # Rank 0's real solve emitted checkpoint telemetry.
    assert any(r["rank"] == 0 and r["name"] == "checkpoint.write"
               for r in records)

    merged = merge_trace_dir(tdir)
    pids = {ev["pid"] for ev in merged["traceEvents"]}
    assert pids == {0, 1}
    # The merged document itself is a valid Chrome trace.
    _load_trace(str(tmp_path / "trace-merged.trace.json"))


# ---------------------------------------------------------------------------
# Streaming: parity and recording
# ---------------------------------------------------------------------------


def test_streaming_keeps_iterations_bit_for_bit():
    p = Problem(M=40, N=40)
    baseline = pcg_solve(p)
    sink = stream.StreamSink()
    stream.set_sink(sink)
    streamed = pcg_solve(p, stream_every=7)
    stream.drain()
    assert int(streamed.iterations) == int(baseline.iterations) == 50
    np.testing.assert_array_equal(np.asarray(streamed.w),
                                  np.asarray(baseline.w))
    ks = [k for k, _ in sink.samples]
    assert ks == [7, 14, 21, 28, 35, 42, 49]
    diffs = [d for _, d in sink.samples]
    assert all(np.isfinite(d) for d in diffs)
    assert diffs[-1] < diffs[0]     # it is a convergence curve


def test_streaming_without_sink_drops_samples():
    p = Problem(M=40, N=40)
    res = pcg_solve(p, stream_every=7)   # no sink installed
    assert int(res.iterations) == 50


def test_streamed_resilient_solve_keeps_counts():
    p = Problem(M=40, N=40)
    sink = stream.StreamSink()
    stream.set_sink(sink)
    res = pcg_solve_resilient(p, chunk=10, stream_every=5)
    stream.drain()
    assert int(res.iterations) == 50
    assert [k for k, _ in sink.samples] == list(range(5, 51, 5))


# ---------------------------------------------------------------------------
# Metrics snapshots and merging
# ---------------------------------------------------------------------------


def test_metrics_snapshot_and_merge(tmp_path):
    metrics.inc("a.count")
    metrics.inc("a.count", 2)
    metrics.gauge("g", 1.5)
    path = str(tmp_path / "m.json")
    metrics.write_snapshot(path, rank=0)
    with open(path) as f:
        snap = json.load(f)
    assert snap["counters"]["a.count"] == 3
    assert snap["gauges"]["g"] == 1.5
    assert "at_unix" in snap and "at_mono" in snap
    other = {"rank": 1, "counters": {"a.count": 4, "b": 1},
             "gauges": {"g": 9.0}}
    merged = metrics.merge([snap, other])
    assert merged["counters"] == {"a.count": 7, "b": 1}
    assert merged["gauges_by_rank"]["0"]["g"] == 1.5
    assert merged["gauges_by_rank"]["1"]["g"] == 9.0


# ---------------------------------------------------------------------------
# Watchdog: monotonic diagnostics with recent telemetry events
# ---------------------------------------------------------------------------


def test_watchdog_diagnostics_carry_monotonic_and_recent_events(tmp_path):
    from poisson_tpu.parallel.watchdog import Watchdog

    obs.configure(trace_dir=str(tmp_path))
    obs.event("solve.phase", phase="chunk-3")
    hb = str(tmp_path / "hb.json")
    fired = {}
    wd = Watchdog(heartbeat_path=hb, timeout=0.1, poll_interval=0.02,
                  on_timeout=lambda diag: fired.update(diag))
    with wd:
        wd.beat(k=30, diff=1e-3)
        import time as _time

        deadline = _time.monotonic() + 5.0
        while not wd.fired and _time.monotonic() < deadline:
            _time.sleep(0.02)
    assert wd.fired
    # The heartbeat file carries both clocks.
    with open(hb) as f:
        beat = json.load(f)
    assert "at_unix" in beat and "at_mono" in beat
    # The diagnostics file: monotonic stall arithmetic + wall view +
    # the recent unified-telemetry events (what the solve was doing).
    with open(hb + ".stalled.json") as f:
        diag = json.load(f)
    assert diag["elapsed_seconds"] >= 0.1          # monotonic verdict
    assert diag["elapsed_wall_seconds"] is not None
    assert "at_mono" in diag
    names = [e["name"] for e in diag["recent_events"]]
    assert "solve.phase" in names and "watchdog.beat" in names
    assert metrics.get("watchdog.stalls") == 1
    assert metrics.get("watchdog.beats") == 1


# ---------------------------------------------------------------------------
# CLI end to end (the PR acceptance command) + selfcheck
# ---------------------------------------------------------------------------


def test_cli_acceptance_command(tmp_path, capsys):
    from poisson_tpu.cli import main

    tdir = str(tmp_path / "tr")
    mpath = str(tmp_path / "m.json")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rc = main(["--M", "40", "--N", "40", "--resilient",
                   "--fault-nan-at", "5", "--trace-dir", tdir,
                   "--metrics-out", mpath, "--json"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(out)
    # Joinable with bench session records: backend + device_kind.
    assert rec["backend"] == "xla"
    assert rec["device_kind"]
    # Same final iterate as the un-instrumented run (the recovered solve
    # converges to tolerance at the golden count).
    assert rec["iterations"] == 50
    assert rec["restarts"] == 1
    # Metrics: the restart counter matches the injected fault.
    with open(mpath) as f:
        m = json.load(f)
    assert m["counters"]["resilient.restarts"] == 1
    # Perfetto-loadable trace.
    events = _load_trace(tdir + "/trace-rank0.trace.json")
    assert any(ev["name"] == "resilient.restart" for ev in events)
    assert any(ev["name"] == "solve.report" for ev in events)


def test_cli_grid_flag_aliases(capsys):
    from poisson_tpu.cli import main

    with pytest.raises(SystemExit, match="not both"):
        main(["40", "40", "--M", "40"])
    with pytest.raises(SystemExit, match="missing grid size N"):
        main(["--M", "40"])


def test_cli_stream_every_guard():
    from poisson_tpu.cli import main

    with pytest.raises(SystemExit, match="stream-every"):
        main(["40", "40", "--backend", "native", "--stream-every", "5"])
    with pytest.raises(SystemExit, match="stream-every"):
        main(["40", "40", "--backend", "sharded", "--stream-every", "5"])


def test_cli_telemetry_off_leaves_no_recorder(capsys):
    """With the flags off the CLI must not configure telemetry (golden
    counts bit-for-bit is structural: no recorder, no stream, no trace)."""
    from poisson_tpu.cli import main

    assert main(["40", "40", "--backend", "xla", "--json"]) == 0
    assert obs.recorder() is None
    assert stream.get_sink() is None
    assert json.loads(
        capsys.readouterr().out.strip().splitlines()[-1]
    )["iterations"] == 50


def test_selfcheck_round_trip(tmp_path, capsys):
    from poisson_tpu.obs.selfcheck import main as selfcheck_main

    assert selfcheck_main(["--dir", str(tmp_path / "sc")]) == 0
    assert "obs selfcheck OK" in capsys.readouterr().out


def test_forensics_report_renders(tmp_path, capsys):
    """summarize_session --telemetry renders the forensics report from a
    real CLI telemetry directory."""
    import subprocess
    import sys as _sys

    from poisson_tpu.cli import main

    tdir = str(tmp_path / "tr")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert main(["--M", "40", "--N", "40", "--resilient",
                     "--fault-nan-at", "5", "--stream-every", "10",
                     "--trace-dir", tdir, "--json"]) == 0
    capsys.readouterr()
    proc = subprocess.run(
        [_sys.executable, "benchmarks/summarize_session.py",
         "--telemetry", tdir],
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr
    assert "Solve forensics" in proc.stdout
    assert "resilient.restart" in proc.stdout
    assert "Streamed convergence" in proc.stdout
    assert "MLUPS" in proc.stdout
