"""Checkpoint/resume: chunked solves equal one-shot solves, and a restart
resumes from the last chunk boundary instead of iteration zero."""

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.solvers.checkpoint import (
    load_state,
    pcg_solve_checkpointed,
    save_state,
)
from poisson_tpu.solvers.pcg import pcg_solve


def test_chunked_equals_oneshot(tmp_path):
    p = Problem(M=40, N=40)
    ref = pcg_solve(p)
    got = pcg_solve_checkpointed(p, str(tmp_path / "ck.npz"), chunk=7)
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-12
    )
    # Converged run cleans its checkpoint up.
    assert not (tmp_path / "ck.npz").exists()


def test_resume_from_partial_checkpoint(tmp_path):
    """Simulate preemption: stop at an iteration cap, then resume with the
    full budget — total work and answer match the one-shot solve."""
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")

    capped = p.with_(max_iter=20)
    partial = pcg_solve_checkpointed(capped, path, chunk=10)
    assert int(partial.iterations) == 20
    # Unconverged cap-hit keeps the checkpoint even without keep_checkpoint.
    assert (tmp_path / "ck.npz").exists()

    # max_iter is excluded from the fingerprint: the uncapped rerun resumes
    # from iteration 20 and converges identically to a one-shot solve.
    ref = pcg_solve(p)
    resumed = pcg_solve_checkpointed(p, path, chunk=10)
    assert int(resumed.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(resumed.w), np.asarray(ref.w), rtol=0, atol=1e-12
    )
    assert not (tmp_path / "ck.npz").exists()  # converged → cleaned up


def test_fingerprint_refuses_different_problem(tmp_path):
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    pcg_solve_checkpointed(p.with_(max_iter=20), path, chunk=10)
    # delta is part of problem identity (unlike max_iter).
    with pytest.raises(ValueError, match="different problem"):
        pcg_solve_checkpointed(p.with_(delta=1e-4), path, chunk=10)


def test_state_roundtrip(tmp_path):
    p = Problem(M=20, N=20)
    ref = pcg_solve(p)
    path = str(tmp_path / "s.npz")

    partial = pcg_solve_checkpointed(p.with_(max_iter=5), path, chunk=5,
                                     keep_checkpoint=True)
    state = load_state(path, _fp(p.with_(max_iter=5)))
    assert int(state.k) == 5
    save_state(path, state, _fp(p.with_(max_iter=5)))
    state2 = load_state(path, _fp(p.with_(max_iter=5)))
    np.testing.assert_array_equal(np.asarray(state.w), np.asarray(state2.w))
    assert int(partial.iterations) == 5
    assert int(ref.iterations) > 5


def _fp(problem):
    from poisson_tpu.solvers.checkpoint import _fingerprint
    from poisson_tpu.solvers.pcg import resolve_dtype, resolve_scaled

    d = resolve_dtype(None)
    return _fingerprint(problem, d, resolve_scaled(None, d))
