"""Checkpoint/resume: chunked solves equal one-shot solves, and a restart
resumes from the last chunk boundary instead of iteration zero."""

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.solvers.checkpoint import (
    load_state,
    pcg_solve_checkpointed,
    save_state,
)
from poisson_tpu.solvers.pcg import pcg_solve


def test_chunked_equals_oneshot(tmp_path):
    p = Problem(M=40, N=40)
    ref = pcg_solve(p)
    got = pcg_solve_checkpointed(p, str(tmp_path / "ck.npz"), chunk=7)
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-12
    )
    # Converged run cleans its checkpoint up.
    assert not (tmp_path / "ck.npz").exists()


def test_resume_from_partial_checkpoint(tmp_path):
    """Simulate preemption: stop after a few chunks (iteration cap), then
    resume with the full budget — total work and answer match one-shot."""
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")

    capped = p.with_(max_iter=20)
    partial = pcg_solve_checkpointed(capped, path, chunk=10,
                                     keep_checkpoint=True)
    assert int(partial.iterations) == 20
    assert (tmp_path / "ck.npz").exists()

    # A fingerprint must bind the checkpoint to its problem: the capped
    # run's fingerprint differs (max_iter), so resuming the uncapped
    # problem with it must refuse...
    with pytest.raises(ValueError, match="different problem"):
        pcg_solve_checkpointed(p, path, chunk=10)

    # ...while resuming the same (capped→extended by new object with same
    # tuple) configuration continues from iteration 20.
    extended = capped.with_(max_iter=20)  # identical fingerprint
    again = pcg_solve_checkpointed(extended, path, chunk=10,
                                   keep_checkpoint=True)
    assert int(again.iterations) == 20  # already at cap: no extra work

    ref = pcg_solve(p)
    full = pcg_solve_checkpointed(p, str(tmp_path / "ck2.npz"), chunk=13)
    assert int(full.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(full.w), np.asarray(ref.w), rtol=0, atol=1e-12
    )


def test_state_roundtrip(tmp_path):
    p = Problem(M=20, N=20)
    ref = pcg_solve(p)
    path = str(tmp_path / "s.npz")

    partial = pcg_solve_checkpointed(p.with_(max_iter=5), path, chunk=5,
                                     keep_checkpoint=True)
    state = load_state(path, _fp(p.with_(max_iter=5)))
    assert int(state.k) == 5
    save_state(path, state, _fp(p.with_(max_iter=5)))
    state2 = load_state(path, _fp(p.with_(max_iter=5)))
    np.testing.assert_array_equal(np.asarray(state.w), np.asarray(state2.w))
    assert int(partial.iterations) == 5
    assert int(ref.iterations) > 5


def _fp(problem):
    from poisson_tpu.solvers.checkpoint import _fingerprint
    from poisson_tpu.solvers.pcg import resolve_dtype, resolve_scaled

    d = resolve_dtype(None)
    return _fingerprint(problem, d, resolve_scaled(None, d))
