"""Geometry-as-a-request suite (``-m geom``).

Pins the whole geometry subsystem (``poisson_tpu/geometry/`` and its
threading through the solver/serve layers):

- DSL normalization and fingerprint stability — permuted unions,
  rotated/reversed polygon rings, and swapped rectangle corners hash
  equal; JSON round-trips preserve fingerprints;
- ellipse-spec canvas bit-parity with ``fictitious_domain.build_fields``
  (the default spec IS the reference setup, to the last ULP) and
  default-path solve parity (``geometry=None`` vs the explicit default
  spec, bit-for-bit);
- manufactured-solution L2 at the discretisation floor, one oracle per
  shipped family — the same rule BENCH.md applies to the ellipse;
- mixed-geometry batched/lane solves match per-geometry sequential
  solves bit-for-bit, inside ONE bucket executable (cache counters
  prove no recompile on the second family);
- a seeded random-polygon sweep (the geometry-space analog of
  ``test_random_geometry.py``'s grid/mesh sweep): sampled canvases vs
  an independent fractional-membership estimate, and the solve
  converging with a finite, bounded solution;
- shape gradients vs finite differences (``solvers.adjoint``);
- sentinel cohort pins: ``detail.geometry_mix`` is experiment identity.
"""

import json

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.geometry import (
    DEFAULT_ELLIPSE,
    Difference,
    Ellipse,
    Intersection,
    Polygon,
    Rectangle,
    SDF,
    Union,
    build_geometry_fields,
    fingerprint_of,
    parse_geometry,
)
from poisson_tpu.geometry.canvas import reset_geometry_cache
from poisson_tpu.geometry.manufactured import (
    case_by_name,
    cases,
    manufactured_error,
)

pytestmark = pytest.mark.geom


# -- DSL normalization & fingerprints -----------------------------------


def test_default_ellipse_is_reference_domain():
    e = DEFAULT_ELLIPSE
    assert (e.cx, e.cy, e.rx, e.ry) == (0.0, 0.0, 1.0, 0.5)
    assert bool(e.contains(0.0, 0.0, np))
    assert not bool(e.contains(1.0, 0.0, np))
    assert not bool(e.contains(0.0, 0.5, np))


def test_union_fingerprint_permutation_invariant():
    a = Ellipse(cx=0.1, rx=0.5, ry=0.3)
    b = Rectangle(-0.5, -0.3, 0.5, 0.3)
    c = Ellipse(cx=-0.2, rx=0.4, ry=0.2)
    u1 = Union((a, b, c))
    u2 = Union((c, a, b))
    u3 = Union((b, Union((c, a))))        # nested: flattened equal
    assert u1.fingerprint == u2.fingerprint == u3.fingerprint
    # … and a different member set hashes differently.
    assert Union((a, b)).fingerprint != u1.fingerprint


def test_polygon_fingerprint_rotation_and_orientation_invariant():
    ring = ((0.0, 0.0), (0.6, 0.0), (0.6, 0.4), (0.0, 0.4))
    p1 = Polygon(ring)
    p2 = Polygon(ring[2:] + ring[:2])       # rotated start
    p3 = Polygon(ring[::-1])                # reversed orientation
    assert p1.fingerprint == p2.fingerprint == p3.fingerprint


def test_rectangle_corner_order_normalizes():
    r1 = Rectangle(-0.5, -0.3, 0.5, 0.3)
    r2 = Rectangle(-0.5, -0.3, 0.5, 0.3).normalize()
    assert r1.fingerprint == r2.fingerprint
    # Parsed JSON round trip preserves the fingerprint.
    assert parse_geometry(r1.to_json()).fingerprint == r1.fingerprint


def test_json_round_trip_every_family():
    specs = [
        DEFAULT_ELLIPSE,
        Rectangle(-0.5, -0.3, 0.5, 0.3),
        Polygon(((0.0, 0.0), (0.6, 0.0), (0.3, 0.4))),
        Union((Ellipse(rx=0.4, ry=0.2), Rectangle(-0.2, -0.2, 0.2, 0.2))),
        Intersection((Ellipse(rx=0.6, ry=0.4),
                      Rectangle(-0.5, -0.5, 0.5, 0.5))),
        Difference(Ellipse(rx=0.7, ry=0.4),
                   Rectangle(-0.2, -0.1, 0.2, 0.1)),
    ]
    for spec in specs:
        back = parse_geometry(spec.to_json())
        assert back.fingerprint == spec.fingerprint, spec


def test_sdf_spec_needs_name_and_rejects_json_parse():
    with pytest.raises(ValueError, match="name"):
        SDF(lambda x, y: x + y)
    s = SDF(lambda x, y: x * x + y * y - 0.16, name="circle-0.4")
    assert s.fingerprint == SDF(lambda x, y: 0.0 * x,
                                name="circle-0.4").fingerprint
    with pytest.raises(ValueError, match="callable"):
        parse_geometry(s.to_json())


def test_parse_rejects_unknown_and_malformed():
    with pytest.raises(ValueError, match="unknown geometry type"):
        parse_geometry({"type": "torus"})
    with pytest.raises(ValueError, match="missing field"):
        parse_geometry({"type": "rect", "x0": 0})
    with pytest.raises(ValueError, match="JSON"):
        parse_geometry("{not json")
    with pytest.raises(ValueError):
        Ellipse(rx=-1.0)
    with pytest.raises(ValueError):
        Rectangle(0.5, 0.0, -0.5, 0.3)


def test_fingerprint_of_sentinel():
    assert fingerprint_of(None) == "default"
    assert fingerprint_of(DEFAULT_ELLIPSE).startswith("g")


# -- canvas compilation -------------------------------------------------


def test_default_ellipse_canvases_bit_identical_to_reference():
    from poisson_tpu.models.fictitious_domain import build_fields

    for M, N in ((40, 40), (17, 23)):
        p = Problem(M=M, N=N)
        a0, b0, r0 = build_fields(p, dtype=np.float64, xp=np)
        a1, b1, r1 = build_geometry_fields(p, DEFAULT_ELLIPSE)
        assert np.array_equal(np.asarray(a0), a1), (M, N)
        assert np.array_equal(np.asarray(b0), b1), (M, N)
        assert np.array_equal(np.asarray(r0), r1), (M, N)


def test_default_spec_solve_bit_identical_to_no_geometry():
    from poisson_tpu.solvers.pcg import pcg_solve

    p = Problem(M=40, N=40)
    plain = pcg_solve(p)
    spec = pcg_solve(p, geometry=DEFAULT_ELLIPSE)
    assert int(plain.iterations) == int(spec.iterations) == 50  # golden
    assert np.array_equal(np.asarray(plain.w), np.asarray(spec.w))


def test_sampled_polygon_matches_closed_form_rectangle():
    p = Problem(M=40, N=40)
    rect = Rectangle(-0.7, -0.4, 0.5, 0.3)
    poly = Polygon(((-0.7, -0.4), (0.5, -0.4), (0.5, 0.3), (-0.7, 0.3)))
    ar, br, _ = build_geometry_fields(p, rect)
    ap, bp, _ = build_geometry_fields(p, poly)
    # 1/eps amplifies face-length error; the bisection pins crossings to
    # ~h·2^-44, so the blended coefficients agree to ~1e-10.
    np.testing.assert_allclose(ar, ap, atol=1e-9)
    np.testing.assert_allclose(br, bp, atol=1e-9)


def test_coefficient_bounds_every_family():
    p = Problem(M=32, N=32)
    for case in cases():
        a, b, _ = build_geometry_fields(p, case.spec)
        for arr in (a, b):
            assert arr.min() >= 1.0 - 1e-12, case.name
            assert arr.max() <= 1.0 / p.eps + 1e-9, case.name


def test_canvas_cache_fingerprint_keyed():
    from poisson_tpu.geometry import geometry_setup
    from poisson_tpu.obs import metrics

    metrics.reset()
    reset_geometry_cache()
    p = Problem(M=24, N=24)
    spec = Ellipse(cx=0.1, rx=0.6, ry=0.35)
    twin = parse_geometry(spec.to_json())     # equal spec, new object
    geometry_setup(p, spec, "float64", False)
    geometry_setup(p, twin, "float64", False)
    # delta/max_iter are solver knobs, not canvas identity.
    geometry_setup(p.with_(delta=1e-9), spec, "float64", False)
    assert metrics.get("geom.cache.misses") == 1
    assert metrics.get("geom.cache.hits") == 2
    geometry_setup(p, Ellipse(cx=0.2, rx=0.6, ry=0.35), "float64", False)
    assert metrics.get("geom.cache.misses") == 2


def test_unbatched_stencil_hlo_unchanged():
    """The batch-axis generalisation must cost the classic path nothing:
    on 2D coefficient fields, apply_A compiles to the byte-identical
    HLO of a literal 2D-only implementation (debug metadata aside)."""
    import jax
    import jax.numpy as jnp

    from poisson_tpu.ops.stencil import apply_A, pad_interior

    def apply_A_2d(w, a, b, h1, h2):
        # The pre-geometry implementation, verbatim.
        wc = w[..., 1:-1, 1:-1]
        ax = (
            a[2:, 1:-1] * (w[..., 2:, 1:-1] - wc)
            - a[1:-1, 1:-1] * (wc - w[..., :-2, 1:-1])
        ) / (h1 * h1)
        ay = (
            b[1:-1, 2:] * (w[..., 1:-1, 2:] - wc)
            - b[1:-1, 1:-1] * (wc - w[..., 1:-1, :-2])
        ) / (h2 * h2)
        return pad_interior(-(ax + ay))

    def hlo(fn):
        from poisson_tpu.contracts.hlo import strip_hlo_metadata

        w = jnp.ones((41, 41))
        a = jnp.ones((41, 41))
        b = jnp.ones((41, 41))
        txt = jax.jit(lambda w, a, b: fn(w, a, b, 0.05, 0.03)).lower(
            w, a, b).compile().as_text()
        return strip_hlo_metadata(txt)

    assert hlo(apply_A) == hlo(apply_A_2d)


# -- manufactured-solution accuracy gates -------------------------------

# Relative L2 floors at the pinned 64×64 grid, measured on CPU fp64 with
# ~2x headroom (the penalty method's boundary layer is O(h); measured
# values 2026-08: ellipse 3.0e-2, ellipse-offset 4.9e-2, rectangle/
# polygon 2.6e-2, union 3.3e-2, intersection 4.7e-2, difference 2.5e-2,
# sdf 7.1e-2). A family drifting past its floor is a real accuracy
# regression, not noise: the solves are deterministic.
_FLOOR_REL = {
    "ellipse": 6e-2,
    "ellipse-offset": 1e-1,
    "rectangle": 6e-2,
    "polygon": 6e-2,
    "union": 7e-2,
    "intersection": 1e-1,
    "difference": 5e-2,
    "sdf": 1.5e-1,
}


@pytest.mark.parametrize("name", sorted(_FLOOR_REL))
def test_manufactured_solution_at_floor(name):
    case = case_by_name(name)
    r = manufactured_error(case, 64, 64)
    assert r["flag"] == 1, r                      # converged
    assert r["rel"] <= _FLOOR_REL[name], r


def test_manufactured_error_shrinks_under_refinement():
    # First-order boundary-layer convergence, checked on the
    # SMOOTH-boundary families (closed-form ellipses and the sampled
    # circle SDF): doubling the resolution must shrink the error. The
    # axis-aligned families are deliberately excluded — their error
    # oscillates with how the box edges align to grid faces
    # (superconvergent when an edge lands on a face), so monotone
    # refinement is not a sound assertion for them; their absolute
    # floors above are the gate.
    for name in ("ellipse", "ellipse-offset", "sdf"):
        coarse = manufactured_error(case_by_name(name), 48, 48)
        fine = manufactured_error(case_by_name(name), 96, 96)
        assert fine["rel"] < 0.8 * coarse["rel"], (name, coarse, fine)


# -- mixed-geometry co-batching -----------------------------------------


def test_mixed_batched_matches_sequential_bitwise():
    from poisson_tpu.solvers.batched import solve_batched
    from poisson_tpu.solvers.pcg import pcg_solve

    p = Problem(M=40, N=40)
    specs = [None, Ellipse(cx=0.1, rx=0.7, ry=0.4),
             Rectangle(-0.6, -0.3, 0.5, 0.3),
             SDF(lambda x, y: x * x + y * y - 0.2, name="circ-test")]
    gates = [1.0, 1.1, 0.9, 1.3]
    res = solve_batched(p, rhs_gates=gates, geometries=specs)
    for i, (g, gate) in enumerate(zip(specs, gates)):
        seq = pcg_solve(p, geometry=g, rhs_gate=gate)
        assert int(res.iterations[i]) == int(seq.iterations), i
        assert np.array_equal(np.asarray(res.w[i]), np.asarray(seq.w)), i


def test_two_families_one_bucket_executable():
    """The acceptance criterion, from the counters: a second geometry
    family on the same grid is a canvas-cache MISS but a bucket-cache
    HIT — new canvases, no recompile."""
    from poisson_tpu.obs import metrics
    from poisson_tpu.solvers.batched import (
        reset_bucket_cache,
        solve_batched,
    )

    metrics.reset()
    reset_bucket_cache()
    reset_geometry_cache()
    p = Problem(M=24, N=24)
    fam_a = Ellipse(cx=0.0, rx=0.8, ry=0.45)
    fam_b = Rectangle(-0.5, -0.4, 0.7, 0.35)
    ra = solve_batched(p, rhs_gates=[1.0] * 3, geometries=[fam_a] * 3)
    assert metrics.get("batched.bucket_cache.misses") == 1
    rb = solve_batched(p, rhs_gates=[1.0] * 3, geometries=[fam_b] * 3)
    assert metrics.get("batched.bucket_cache.hits") == 1
    assert metrics.get("batched.bucket_cache.misses") == 1
    assert metrics.get("geom.cache.misses") == 2   # one bake per family
    assert metrics.get("geom.cache.hits") >= 2     # members reuse it
    assert np.all(np.asarray(ra.flag) == 1)
    assert np.all(np.asarray(rb.flag) == 1)


def test_geometry_none_batch_is_classic_path_bitwise():
    from poisson_tpu.solvers.batched import solve_batched

    p = Problem(M=24, N=24)
    classic = solve_batched(p, rhs_gates=[1.0, 1.3])
    geo = solve_batched(p, rhs_gates=[1.0, 1.3], geometries=[None, None])
    assert np.array_equal(np.asarray(classic.w), np.asarray(geo.w))
    assert np.array_equal(np.asarray(classic.iterations),
                          np.asarray(geo.iterations))


def test_geometries_length_mismatch_rejected():
    from poisson_tpu.solvers.batched import solve_batched

    p = Problem(M=16, N=16)
    with pytest.raises(ValueError, match="one entry per member"):
        solve_batched(p, rhs_gates=[1.0, 1.0],
                      geometries=[DEFAULT_ELLIPSE])


def test_multi_geometry_lanes_splice_and_retire_bitwise():
    from poisson_tpu.solvers.lanes import LaneBatch
    from poisson_tpu.solvers.pcg import pcg_solve

    p = Problem(M=32, N=32)
    lanes = LaneBatch(p, 2, chunk=10, multi_geometry=True)
    g_a = Ellipse(cx=0.1, rx=0.7, ry=0.4)
    lanes.splice("default", 1.0)
    lanes.splice("ell-a", 1.0, geometry=g_a)
    for _ in range(40):
        lanes.step()
        if all(v["done"] or v["member_id"] is None
               for v in lanes.lane_view()):
            break
    r0, r1 = lanes.retire(0), lanes.retire(1)
    s0, sa = pcg_solve(p), pcg_solve(p, geometry=g_a)
    assert r0.iterations == int(s0.iterations)
    assert np.array_equal(np.asarray(r0.w), np.asarray(s0.w))
    assert r1.iterations == int(sa.iterations)
    assert np.array_equal(np.asarray(r1.w), np.asarray(sa.w))
    # Splice a NEW family into the freed lane of the same programs.
    g_b = Rectangle(-0.5, -0.3, 0.6, 0.35)
    lanes.splice("rect-b", 1.0, geometry=g_b)
    for _ in range(40):
        lanes.step()
        if all(v["done"] or v["member_id"] is None
               for v in lanes.lane_view()):
            break
    rb = lanes.retire(lanes.origin.index("rect-b"))
    sb = pcg_solve(p, geometry=g_b)
    assert rb.iterations == int(sb.iterations)
    assert np.array_equal(np.asarray(rb.w), np.asarray(sb.w))


def test_single_geometry_lane_batch_rejects_geometry_splice():
    from poisson_tpu.solvers.lanes import LaneBatch

    lanes = LaneBatch(Problem(M=16, N=16), 1, chunk=5)
    with pytest.raises(ValueError, match="multi_geometry"):
        lanes.splice("m", 1.0, geometry=DEFAULT_ELLIPSE)


# -- serve integration --------------------------------------------------


def test_service_mixed_geometry_both_engines():
    from poisson_tpu.serve import (
        ServicePolicy,
        SolveRequest,
        SolveService,
    )
    from poisson_tpu.serve.types import SCHED_CONTINUOUS
    from poisson_tpu.testing.chaos import VirtualClock, _quiet_degradation

    p = Problem(M=40, N=40)
    specs = [Ellipse(cx=0.1, rx=0.7, ry=0.4),
             Rectangle(-0.6, -0.3, 0.5, 0.3), None]
    for sched in (None, SCHED_CONTINUOUS):
        vc = VirtualClock()
        kw = {"scheduling": sched, "refill_chunk": 10} if sched else {}
        svc = SolveService(
            ServicePolicy(capacity=16,
                          degradation=_quiet_degradation(), **kw),
            clock=vc, sleep=vc.sleep)
        for i in range(6):
            svc.submit(SolveRequest(request_id=i, problem=p,
                                    geometry=specs[i % 3],
                                    rhs_gate=1.0 + i / 10))
        outs = svc.drain()
        assert len(outs) == 6 and all(o.converged for o in outs), sched
        assert svc.stats()["lost"] == 0


def test_geometry_requests_carry_fingerprint_in_flight_trace(tmp_path):
    from poisson_tpu import obs
    from poisson_tpu.obs.trace import load_events
    from poisson_tpu.serve import (
        ServicePolicy,
        SolveRequest,
        SolveService,
    )
    from poisson_tpu.testing.chaos import VirtualClock, _quiet_degradation

    obs.configure(trace_dir=str(tmp_path))
    try:
        p = Problem(M=24, N=24)
        g = Ellipse(cx=0.1, rx=0.6, ry=0.3)
        vc = VirtualClock()
        svc = SolveService(
            ServicePolicy(capacity=4,
                          degradation=_quiet_degradation()),
            clock=vc, sleep=vc.sleep)
        svc.submit(SolveRequest(request_id="geo", problem=p, geometry=g))
        svc.drain()
        obs.finalize()
        events = load_events(str(tmp_path))
    finally:
        obs.shutdown()
    resident = [e for e in events
                if e.get("name") == "flight.span"
                and (e.get("attrs") or {}).get("span") == "lane_resident"]
    assert resident, "no residency span emitted"
    assert any((e.get("attrs") or {}).get("geometry") == g.fingerprint
               for e in resident), resident


def test_geometry_divergence_never_escalates_to_resilient():
    """The resilient escalation driver solves the reference domain; a
    geometry request's divergence retry must stay on the geometry-aware
    dispatch path (escalate stays False)."""
    from poisson_tpu.serve.service import SolveService, _Entry
    from poisson_tpu.serve.types import (
        ERROR_DIVERGENCE,
        ServicePolicy,
        SolveRequest,
    )
    from poisson_tpu.testing.chaos import VirtualClock

    vc = VirtualClock()
    svc = SolveService(ServicePolicy(), clock=vc, sleep=vc.sleep)
    p = Problem(M=16, N=16)
    geo_entry = _Entry(SolveRequest(request_id="g", problem=p,
                                    geometry=DEFAULT_ELLIPSE), 0.0, None)
    plain_entry = _Entry(SolveRequest(request_id="p", problem=p),
                         0.0, None)
    svc._retry_or_fail(geo_entry, ERROR_DIVERGENCE, "boom", set())
    svc._retry_or_fail(plain_entry, ERROR_DIVERGENCE, "boom", set())
    assert geo_entry.escalate is False
    assert plain_entry.escalate is True


def test_journal_replays_geometry_requests(tmp_path):
    from poisson_tpu.serve.journal import SolveJournal, replay_journal
    from poisson_tpu.serve.types import SolveRequest

    path = str(tmp_path / "geo.journal")
    j = SolveJournal(path)
    p = Problem(M=16, N=16)
    g = Ellipse(cx=0.2, rx=0.5, ry=0.3)
    j.submit(SolveRequest(request_id="geo-1", problem=p, geometry=g),
             "trace-1")
    j.record("requeue", request_id="geo-1", attempt=1, error="transient",
             recovered=False, taint=["other"], taint_fp=["gdeadbeef"])
    j.close()
    replay = replay_journal(path)
    (pend,) = replay.pending
    assert pend.request.geometry is not None
    assert pend.request.geometry.fingerprint == g.fingerprint
    assert pend.taint_fp == {"gdeadbeef"}


# -- random-polygon sweep (seeded, alongside test_random_geometry.py) ---


def _random_polygons(n: int):
    rng = np.random.RandomState(20260804)
    out = []
    for _ in range(n):
        k = int(rng.randint(3, 8))
        # A star-shaped simple polygon: random radii at sorted angles
        # around a random interior center, kept inside the solve box.
        cx = float(rng.uniform(-0.25, 0.25))
        cy = float(rng.uniform(-0.12, 0.12))
        ang = np.sort(rng.uniform(0.0, 2 * np.pi, size=k))
        rad = rng.uniform(0.18, 0.42, size=k)
        verts = tuple(
            (float(cx + r * np.cos(a)), float(cy + 0.55 * r * np.sin(a)))
            for a, r in zip(ang, rad))
        out.append(Polygon(verts))
    return out


@pytest.mark.parametrize("poly", _random_polygons(5))
def test_random_polygon_canvases_and_solve(poly):
    p = Problem(M=48, N=48)
    a, b, rhs = build_geometry_fields(p, poly)
    # Canvas sanity: coefficients within the blend bounds, and the
    # vertical-face lengths implied by a agree with an independent
    # dense-membership estimate of the face fraction.
    assert a.min() >= 1.0 - 1e-12 and b.min() >= 1.0 - 1e-12
    assert a.max() <= 1.0 / p.eps + 1e-9
    i, j = p.M // 2, p.N // 2            # a face near the center
    x = p.x_min + i * p.h1 - 0.5 * p.h1
    ys = p.y_min + j * p.h2 - 0.5 * p.h2 + np.linspace(0, p.h2, 4001)
    frac = float(poly.contains(np.full_like(ys, x), ys, np).mean())
    ell = frac * p.h2
    blend = ell / p.h2 + (1 - ell / p.h2) / p.eps
    got = a[i, j]
    want = (1.0 if abs(ell - p.h2) < 1e-9
            else (1.0 / p.eps if ell < 1e-9 else blend))
    # The dense estimate quantizes ℓ at h/4000; 1/eps amplification
    # keeps this loose but a misclassified face fails at O(1/eps).
    assert got == pytest.approx(want, rel=0, abs=2.0), (got, want)
    # The solve: converges, finite, zero on the Dirichlet ring, and the
    # fictitious-domain solution is small outside the polygon.
    from poisson_tpu.solvers.pcg import pcg_solve

    res = pcg_solve(p, geometry=poly)
    w = np.asarray(res.w)
    assert int(res.flag) == 1, poly
    assert np.isfinite(w).all()
    assert abs(w[0, :]).max() == 0 and abs(w[:, 0]).max() == 0
    xs = (p.x_min + np.arange(p.M + 1) * p.h1)[:, None]
    ys2 = (p.y_min + np.arange(p.N + 1) * p.h2)[None, :]
    inside = poly.contains(xs, ys2, np)
    if inside.any() and (~inside).any():
        assert abs(w[~inside]).max() <= max(1e-3,
                                            0.15 * abs(w[inside]).max())


# -- sentinel cohort pins -----------------------------------------------


def test_regress_geometry_mix_splits_cohorts():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "regress", pathlib.Path(__file__).resolve().parents[1]
        / "benchmarks" / "regress.py")
    regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(regress)

    def rec(geometry_mix, value):
        return regress.record_from_result({
            "metric": "serve.sustained_solves_per_sec",
            "value": value,
            "detail": {"grid": [96, 144], "dtype": "float32",
                       "backend": "xla_serve", "devices": 1,
                       "platform": "cpu", "fault_load": "clean",
                       "arrival_rate": 60.0,
                       **({"geometry_mix": geometry_mix}
                          if geometry_mix else {})},
        }, source="test")

    mixed = rec(4, 20.0)
    clean = rec(None, 60.0)
    assert regress.cohort_key(mixed) != regress.cohort_key(clean)
    assert regress.cohort_key(rec(4, 25.0)) == regress.cohort_key(mixed)
    # A slow mixed run among fast single-ellipse baselines must NOT
    # alarm: the cohorts never meet.
    records = [rec(None, 60.0 + i) for i in range(4)] + [mixed]
    verdict = regress.evaluate(records)
    assert all(r["classification"] != "regression"
               for r in verdict["records"]), verdict


def test_chaos_campaign_includes_geometry_scenario():
    from poisson_tpu.testing import chaos

    assert "geometry-mixed-cobatch" in chaos.scenario_names()
    rep = chaos.run_scenario("geometry-mixed-cobatch", seed=0)
    assert rep["ok"], rep["checks"]
    assert rep["invariant"]["lost"] == 0
    assert len(chaos.scenario_names()) >= 21


# -- shape gradients ----------------------------------------------------


def test_shape_gradient_matches_finite_differences():
    import jax.numpy as jnp

    from poisson_tpu.solvers.adjoint import (
        differentiable_geometry_solve,
        shape_gradient,
    )

    # Tight delta: the FD probe differences two solves, so solver
    # tolerance must sit far below the probe step.
    p = Problem(M=32, N=32, delta=1e-11)
    loss = lambda w: jnp.sum(w[1:-1, 1:-1]) * p.h1 * p.h2
    spec_fn = lambda q: Ellipse(cx=0.0, cy=0.0, rx=q[0], ry=q[1])
    params = jnp.asarray([0.8, 0.42])
    val, grad = shape_gradient(p, spec_fn, params, loss)
    assert np.isfinite(float(val)) and np.isfinite(np.asarray(grad)).all()
    eps = 1e-5

    def f(q):
        return float(loss(differentiable_geometry_solve(
            p, spec_fn(jnp.asarray(q)))))

    for k in range(2):
        hi = [0.8, 0.42]
        lo = [0.8, 0.42]
        hi[k] += eps
        lo[k] -= eps
        fd = (f(hi) - f(lo)) / (2 * eps)
        assert float(grad[k]) == pytest.approx(fd, rel=5e-3), (k, fd)


def test_shape_gradient_rejects_sampled_families():
    from poisson_tpu.solvers.adjoint import differentiable_geometry_solve

    with pytest.raises(ValueError, match="closed-form"):
        differentiable_geometry_solve(
            Problem(M=16, N=16),
            Polygon(((0.0, 0.0), (0.4, 0.0), (0.2, 0.3))))


# -- CLI ----------------------------------------------------------------


def test_cli_geometry_subcommand(capsys):
    from poisson_tpu.cli import main

    rc = main(["geometry",
               '{"type":"ellipse","rx":0.7,"ry":0.4}', "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["fingerprint"].startswith("g")
    assert out["inside_nodes"] > 0 and out["cut_faces"] > 0
    rc = main(["geometry", '{"type":"rect","x0":-0.5,"y0":-0.3,'
               '"x1":0.5,"y1":0.3}', "--height", "10"])
    assert rc == 0
    rendered = capsys.readouterr().out
    assert "#" in rendered and "fingerprint" in rendered


def test_cli_geometry_flag_on_solve(capsys):
    from poisson_tpu.cli import main

    rc = main(["24", "24", "--geometry",
               '{"type":"ellipse","rx":0.7,"ry":0.4}', "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["l2_error"] is None       # the ellipse oracle is not it
    assert rep["iterations"] > 0


def test_cli_geometry_flag_rejections(capsys):
    from poisson_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["24", "24", "--geometry", "{bad json"])
    with pytest.raises(SystemExit, match="xla"):
        main(["24", "24", "--backend", "native", "--geometry",
              '{"type":"ellipse"}'])


def test_cli_solve_batched_geometry_mix(capsys):
    from poisson_tpu.cli import main

    rc = main(["solve-batched", "24", "24", "--batch", "4",
               "--geometry", '{"type":"ellipse","rx":0.7,"ry":0.4}',
               "--geometry",
               '{"type":"rect","x0":-0.5,"y0":-0.3,"x1":0.5,"y1":0.3}',
               "--compare-sequential", "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["geometry_mix"] == 2
    assert len(rep["geometries"]) == 2
    assert rep["iterations_match_sequential"] is True
    assert rep["converged"] == 4
