"""CLI driver: the reference's `main()` surface, as flags (SURVEY §7.6)."""

import json

import pytest

from poisson_tpu.cli import build_parser, main
from poisson_tpu.config import Problem


def _json_line(capsys) -> dict:
    out = capsys.readouterr().out.strip().splitlines()
    return json.loads(out[-1])


def test_xla_backend_json(capsys):
    assert main(["40", "40", "--backend", "xla", "--json"]) == 0
    rec = _json_line(capsys)
    assert rec["iterations"] == 50
    assert rec["final_diff"] < 1e-6
    assert rec["l2_error"] < 5e-3


def test_native_backend_json(capsys):
    assert main(["40", "40", "--backend", "native", "--threads", "1",
                 "--json"]) == 0
    rec = _json_line(capsys)
    assert rec["iterations"] == 50
    assert rec["dtype"] == "float64"


def test_sharded_backend_mesh(capsys):
    assert main(["40", "40", "--backend", "sharded", "--mesh", "2x4",
                 "--json"]) == 0
    rec = _json_line(capsys)
    assert rec["iterations"] == 50
    assert rec["mesh"] == [2, 4]


def test_unweighted_norm_flag(capsys):
    assert main(["40", "40", "--backend", "xla", "--unweighted-norm",
                 "--json"]) == 0
    # stage0's unweighted norm: 61 in the fp64 oracle, 62 within one ulp.
    assert _json_line(capsys)["iterations"] in (61, 62)


def test_table_output_and_categories(capsys):
    assert main(["40", "40", "--backend", "xla", "--categories"]) == 0
    out = capsys.readouterr().out
    assert "Iter=50" in out
    assert "stencil (mat_A)" in out


def test_bad_mesh_spec_rejected():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["40", "40", "--mesh", "banana"])


def test_ca_sharded_bad_bm_exits_cleanly():
    """A --bm that is not a positive multiple of 8 must exit like every
    other flag-validation path, not surface ca_shard_spec's ValueError as
    a traceback (round-5 advice)."""
    with pytest.raises(SystemExit, match="positive multiple of 8"):
        main(["40", "40", "--backend", "pallas-ca-sharded", "--bm", "13"])


def test_sharded_checkpoint_cli(capsys, tmp_path):
    ck = str(tmp_path / "ck.npz")
    assert main(["40", "40", "--backend", "sharded", "--mesh", "2x4",
                 "--checkpoint", ck, "--chunk", "10", "--json"]) == 0
    rec = _json_line(capsys)
    assert rec["iterations"] == 50
    assert rec["mesh"] == [2, 4]


def test_checkpoint_misuse_rejected():
    with pytest.raises(SystemExit):
        main(["40", "40", "--backend", "native", "--checkpoint", "/tmp/x.npz"])
    with pytest.raises(SystemExit):
        main(["40", "40", "--backend", "sharded", "--setup", "device",
              "--checkpoint", "/tmp/x.npz"])
    # Explicit xla + mesh + checkpoint must error, not silently drop --mesh.
    with pytest.raises(SystemExit):
        main(["40", "40", "--backend", "xla", "--mesh", "2x4",
              "--checkpoint", "/tmp/x.npz"])
    # auto + explicit --mesh + --setup device + --checkpoint must also
    # error: the single-device fallback would silently drop the mesh.
    with pytest.raises(SystemExit):
        main(["40", "40", "--mesh", "2x4", "--setup", "device",
              "--checkpoint", "/tmp/x.npz"])


def test_auto_backend_device_setup_checkpoint_falls_back(capsys, tmp_path):
    """auto + --setup device + --checkpoint on a multi-device host must not
    error (it predates the sharded auto-pick): it falls back to the
    single-device xla checkpointed path. Only the explicit
    ``--backend sharded`` spelling earns the SystemExit."""
    ck = str(tmp_path / "ck.npz")
    assert main(["40", "40", "--setup", "device", "--checkpoint", ck,
                 "--chunk", "10", "--json"]) == 0
    rec = _json_line(capsys)
    assert rec["iterations"] == 50
    # Single-device xla path: no mesh, one device.
    assert rec["mesh"] is None
    assert rec["devices"] == 1


def test_converged_solve_skips_final_checkpoint_write(tmp_path, monkeypatch):
    """The final converging chunk's state would be deleted immediately —
    run_chunked must not gather + write it (a wasted collective and disk
    write on every converged solve at pod scale)."""
    import poisson_tpu.solvers.checkpoint as ckpt

    writes = []
    real_save = ckpt.save_state
    monkeypatch.setattr(
        ckpt, "save_state",
        lambda path, state, fp, **kw: (writes.append(int(state.k)),
                                       real_save(path, state, fp, **kw)),
    )
    p = Problem(M=40, N=40)
    got = ckpt.pcg_solve_checkpointed(p, str(tmp_path / "ck.npz"), chunk=7)
    assert int(got.iterations) == 50
    # Chunks end at 7,14,...,49; the converging chunk (50) is never saved.
    assert writes and max(writes) < 50
    assert not (tmp_path / "ck.npz").exists()


def test_pallas_geometry_flags(capsys, tmp_path):
    """--bm/--bn/--parallel-grid reach the fused path (interpret on CPU),
    including the checkpointed variant (the portable format is geometry-
    independent)."""
    assert main(["40", "40", "--backend", "pallas", "--bm", "16",
                 "--bn", "128", "--parallel-grid", "--json"]) == 0
    assert _json_line(capsys)["iterations"] == 50
    assert main(["40", "40", "--backend", "pallas", "--bn", "128",
                 "--checkpoint", str(tmp_path / "ck.npz"), "--chunk", "10",
                 "--json"]) == 0
    assert _json_line(capsys)["iterations"] == 50


def test_pallas_checkpoint_cli(capsys, tmp_path):
    ck = str(tmp_path / "ck.npz")
    assert main(["40", "40", "--backend", "pallas", "--checkpoint", ck,
                 "--chunk", "10", "--json"]) == 0
    assert _json_line(capsys)["iterations"] == 50


def test_ca_sharded_backend_cli(capsys):
    """--backend pallas-ca-sharded reaches the distributed CA path
    (interpret on the virtual CPU mesh) with its geometry flags."""
    assert main(["40", "40", "--backend", "pallas-ca-sharded",
                 "--mesh", "2x2", "--bm", "16", "--json"]) == 0
    line = _json_line(capsys)
    assert line["iterations"] == 50
    assert line["mesh"] == [2, 2]
    assert line["dtype"] == "float32"


def test_ca_sharded_checkpoint_cli(capsys, tmp_path):
    """--checkpoint on the sharded CA path: the chunked driver must
    reproduce the one-shot result (portable cross-algorithm format)."""
    ck = str(tmp_path / "ck.npz")
    assert main(["40", "40", "--backend", "pallas-ca-sharded",
                 "--mesh", "2x2", "--checkpoint", ck, "--chunk", "10",
                 "--json"]) == 0
    assert _json_line(capsys)["iterations"] == 50
