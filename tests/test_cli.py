"""CLI driver: the reference's `main()` surface, as flags (SURVEY §7.6)."""

import json

import pytest

from poisson_tpu.cli import build_parser, main


def _json_line(capsys) -> dict:
    out = capsys.readouterr().out.strip().splitlines()
    return json.loads(out[-1])


def test_xla_backend_json(capsys):
    assert main(["40", "40", "--backend", "xla", "--json"]) == 0
    rec = _json_line(capsys)
    assert rec["iterations"] == 50
    assert rec["final_diff"] < 1e-6
    assert rec["l2_error"] < 5e-3


def test_native_backend_json(capsys):
    assert main(["40", "40", "--backend", "native", "--threads", "1",
                 "--json"]) == 0
    rec = _json_line(capsys)
    assert rec["iterations"] == 50
    assert rec["dtype"] == "float64"


def test_sharded_backend_mesh(capsys):
    assert main(["40", "40", "--backend", "sharded", "--mesh", "2x4",
                 "--json"]) == 0
    rec = _json_line(capsys)
    assert rec["iterations"] == 50
    assert rec["mesh"] == [2, 4]


def test_unweighted_norm_flag(capsys):
    assert main(["40", "40", "--backend", "xla", "--unweighted-norm",
                 "--json"]) == 0
    # stage0's unweighted norm: 61 in the fp64 oracle, 62 within one ulp.
    assert _json_line(capsys)["iterations"] in (61, 62)


def test_table_output_and_categories(capsys):
    assert main(["40", "40", "--backend", "xla", "--categories"]) == 0
    out = capsys.readouterr().out
    assert "Iter=50" in out
    assert "stencil (mat_A)" in out


def test_bad_mesh_spec_rejected():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["40", "40", "--mesh", "banana"])


def test_sharded_checkpoint_cli(capsys, tmp_path):
    ck = str(tmp_path / "ck.npz")
    assert main(["40", "40", "--backend", "sharded", "--mesh", "2x4",
                 "--checkpoint", ck, "--chunk", "10", "--json"]) == 0
    rec = _json_line(capsys)
    assert rec["iterations"] == 50
    assert rec["mesh"] == [2, 4]


def test_checkpoint_misuse_rejected():
    with pytest.raises(SystemExit):
        main(["40", "40", "--backend", "native", "--checkpoint", "/tmp/x.npz"])
    with pytest.raises(SystemExit):
        main(["40", "40", "--backend", "sharded", "--setup", "device",
              "--checkpoint", "/tmp/x.npz"])
    # Explicit xla + mesh + checkpoint must error, not silently drop --mesh.
    with pytest.raises(SystemExit):
        main(["40", "40", "--backend", "xla", "--mesh", "2x4",
              "--checkpoint", "/tmp/x.npz"])


def test_pallas_checkpoint_cli(capsys, tmp_path):
    ck = str(tmp_path / "ck.npz")
    assert main(["40", "40", "--backend", "pallas", "--checkpoint", ck,
                 "--chunk", "10", "--json"]) == 0
    assert _json_line(capsys)["iterations"] == 50
