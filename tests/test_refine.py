"""Mixed-precision iterative refinement: fp64-floor algebraic accuracy out
of the fp32 fused path (solvers.refine — a capability the all-fp64
reference gets only by paying fp64 cost for every sweep)."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.ops.stencil import apply_A
from poisson_tpu.solvers.pcg import pcg_solve
from poisson_tpu.solvers.refine import (
    _fields,
    _weighted_norm,
    apply_A64_host,
    refined_solve,
)


def _scaled_rel_residual(p, w):
    a64, b64, rhs64, sc64 = _fields(p)
    r = rhs64 - apply_A64_host(p, a64, b64, w)
    return _weighted_norm(p, sc64 * r) / _weighted_norm(p, sc64 * rhs64)


def test_host_operator_matches_stencil():
    """The fp64 host residual operator is the same operator the device
    applies (pinned against ops.stencil.apply_A under x64)."""
    p = Problem(M=12, N=16)
    a64, b64, _, _ = _fields(p)
    rng = np.random.default_rng(0)
    w = np.zeros((p.M + 1, p.N + 1))
    w[1:-1, 1:-1] = rng.standard_normal((p.M - 1, p.N - 1))
    want = np.asarray(
        apply_A(jnp.asarray(w), jnp.asarray(a64), jnp.asarray(b64),
                p.h1, p.h2)
    )
    got = apply_A64_host(p, a64, b64, w)
    np.testing.assert_allclose(got, want, rtol=1e-13, atol=1e-13)


@pytest.mark.parametrize(
    "M,N",
    [(40, 40), pytest.param(400, 600, marks=pytest.mark.slow)],
)
def test_refinement_reaches_fp64_floor(M, N):
    """A few fp32 inner solves drive the TRUE fp64 scaled-system residual
    to <= 1e-10 relative — far below anything a single fp32 solve can
    reach — with monotonically decreasing residual norms. The first inner
    solve does the oracle's golden iteration count (it IS the standard
    solve); corrections are cheaper or comparable."""
    p = Problem(M=M, N=N)
    res = refined_solve(p, tol=1e-10)
    assert res.converged and res.relative_residual <= 1e-10
    assert _scaled_rel_residual(p, res.w) <= 1e-10
    assert all(
        b < a for a, b in zip(res.residual_norms, res.residual_norms[1:])
    ), res.residual_norms
    assert res.refinements >= 1  # one fp32 solve alone cannot reach 1e-10
    golden = {(40, 40): 50, (400, 600): 546}[(M, N)]
    assert res.inner_iterations[0] == golden


def test_resident_backend_reaches_floor():
    """Refinement over the VMEM-resident inner solver: each correction
    pass is one kernel launch, and the fp64 floor is reached exactly as
    with the fused inner solver."""
    from poisson_tpu.solvers.refine import refined_solve

    p = Problem(M=40, N=40)
    fused = refined_solve(p, tol=1e-10)
    res = refined_solve(p, tol=1e-10, backend="resident")
    assert res.converged
    assert res.relative_residual <= 1e-10
    assert res.refinements <= fused.refinements + 1
    with pytest.raises(ValueError, match="resident"):
        refined_solve(p, backend="resident", bm=16)


def test_refined_matches_tight_fp64_solve():
    """The refined solution agrees with a tightened fp64 XLA solve to
    ~1e-8 — fp64 answers from fp32 device sweeps."""
    p = Problem(M=40, N=40)
    res = refined_solve(p, tol=1e-12, max_refinements=8)
    tight = pcg_solve(dataclasses.replace(p, delta=1e-12), dtype=jnp.float64)
    np.testing.assert_allclose(
        res.w, np.asarray(tight.w), atol=1e-8
    )


def test_zero_rhs_short_circuits():
    p = Problem(M=16, N=16, f_val=0.0)
    res = refined_solve(p)
    assert (res.w == 0).all()
    assert res.inner_iterations == ()
    assert res.converged


def test_unconverged_is_reported():
    """An insufficient refinement budget is visible on the result, not
    silent."""
    p = Problem(M=40, N=40)
    res = refined_solve(p, tol=1e-14, max_refinements=0)
    assert not res.converged
    assert res.relative_residual > 1e-14
