"""Krylov-memory suite: block-CG batched mode + fingerprint recycling.

Covers the PR 14 contracts end to end:

- the default path is untouched — ``solve_batched(mode="independent")``
  is bit-identical to the historical call and the committed ledger pins
  its lowering to the SAME fingerprint as ``batched.mesh_none_f64``;
- block mode converges every geometry family at its manufactured-
  solution L2 floor, cuts total iterations on a clustered batch, and
  degrades gracefully (never breaks down) on rank-deficient batches;
- deflation recycling: warm-start-beats-cold on a repeat fingerprint,
  cache invalidation on dtype change / escalation / SDC-suspect
  cohorts / journal recovery (a recovered process REBUILDS the basis),
  byte-budget eviction, and the poisoned-basis fallback that never
  returns a wrong answer;
- the serve layer: ``:blk``/``:defl`` cohort splits, block batch
  formation requiring one shared operator, basis-holder sticky
  routing, loud submission validation;
- the regression sentinel: ``krylov_mode``/``deflation``/
  ``repeat_fingerprint`` join the cohort key so warm/block runs never
  judge cold/independent baselines.
"""

import json

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.geometry.dsl import Ellipse, Rectangle
from poisson_tpu.geometry.manufactured import case_by_name, cases
from poisson_tpu.krylov import (
    KRYLOV_BLOCK,
    KRYLOV_INDEPENDENT,
    KrylovPolicy,
    resolve_krylov,
)
from poisson_tpu.krylov import recycle
from poisson_tpu.krylov.block import (
    _solve_block,
    block_l2_errors,
    clustered_ellipse_stack,
)
from poisson_tpu.obs import metrics
from poisson_tpu.solvers.batched import reset_bucket_cache, solve_batched
from poisson_tpu.solvers.pcg import FLAG_CONVERGED, host_setup, pcg_solve

pytestmark = pytest.mark.krylov

DEFL = KrylovPolicy(deflation=True)
BLK = KrylovPolicy(mode="block")

# Per-family relative-L2 floors for the krylov modes at 100x150 f32,
# measured with 2x headroom — the same rule (and roughly the same
# numbers) as the base-path floors in tests/test_geometry_dsl.py: the
# Krylov programs must land at the family's established floor, not at
# a new one.
FAMILY_FLOORS = {
    "ellipse": 0.038,
    "ellipse-offset": 0.065,
    "rectangle": 0.024,
    "polygon": 0.024,
    "union": 0.059,
    "intersection": 0.023,
    "difference": 0.020,
    "sdf": 0.071,
}


@pytest.fixture(autouse=True)
def _clean_registries():
    metrics.reset()
    reset_bucket_cache()
    recycle.reset_krylov_cache()
    yield
    metrics.reset()
    reset_bucket_cache()
    recycle.reset_krylov_cache()


# -- policy resolution ---------------------------------------------------

def test_resolve_krylov_defaults_and_rejections():
    assert resolve_krylov(None).mode == KRYLOV_INDEPENDENT
    assert not resolve_krylov(None).deflation
    with pytest.raises(ValueError, match="unknown krylov mode"):
        resolve_krylov(KrylovPolicy(mode="blockish"))
    with pytest.raises(ValueError, match="does not compose"):
        resolve_krylov(KrylovPolicy(mode=KRYLOV_BLOCK, deflation=True))
    with pytest.raises(ValueError, match="harvest"):
        resolve_krylov(KrylovPolicy(deflation=True, harvest=4, keep=8))


# -- default path untouched ----------------------------------------------

def test_mode_independent_is_the_historical_call():
    p = Problem(M=40, N=40)
    a = solve_batched(p, rhs_gates=[1.0, 1.3])
    b = solve_batched(p, rhs_gates=[1.0, 1.3], mode="independent")
    assert np.array_equal(np.asarray(a.w), np.asarray(b.w))
    assert np.array_equal(np.asarray(a.iterations),
                          np.asarray(b.iterations))
    assert a.deficient is None and b.deficient is None


def test_ledger_pins_mode_independent_to_the_historical_program():
    """The committed ledger carries the mode='independent' lowering
    with the SAME fingerprint as the pre-krylov bucket executable —
    the byte-identity half of the acceptance criteria, from the
    artifact the gate actually checks."""
    from poisson_tpu.contracts.manifest import LEDGER_PATH

    with open(LEDGER_PATH) as f:
        entries = json.load(f)["entries"]
    assert "batched.mode_independent_f64" in entries
    assert (entries["batched.mode_independent_f64"]["fingerprint"]
            == entries["batched.mesh_none_f64"]["fingerprint"])


# -- block mode ----------------------------------------------------------

def test_block_rank_deficient_batch_degrades_gracefully():
    """Pure RHS rescalings — an exactly rank-1 block — must converge
    every member at (about) the single-solve rate with the deficiency
    DETECTED, not break down: the O'Leary remedy, measured."""
    p = Problem(M=60, N=60)
    solo = int(pcg_solve(p, dtype="float32").iterations)
    r = solve_batched(p, rhs_gates=[1.0, 1.4, 0.7], dtype="float32",
                      mode="block")
    assert (np.asarray(r.flag) == FLAG_CONVERGED).all()
    assert bool(np.asarray(r.deficient))
    assert int(np.asarray(r.max_iterations)) <= solo + 5


def test_block_cuts_total_iterations_on_clustered_batch():
    """The headline lever at test scale: ≥15%% total-iteration cut on
    the clustered-RHS batch (the 400x600 bench measures ≥25%% — same
    construction, BENCH.md)."""
    p = Problem(M=160, N=240)
    B = 8
    fs, us, inside = clustered_ellipse_stack(p, B)
    ri = solve_batched(p, rhs_stack=fs, dtype="float32")
    rb = solve_batched(p, rhs_stack=fs, dtype="float32", mode="block")
    assert (np.asarray(rb.flag) == FLAG_CONVERGED).all()
    indep_total = int(np.asarray(ri.iterations).sum())
    block_total = B * int(np.asarray(rb.max_iterations))
    cut = 1.0 - block_total / indep_total
    assert cut >= 0.15, (indep_total, block_total)
    # …at the same L2 floor, each member against its EXACT solution.
    l2_i = block_l2_errors(p, ri, us, inside)
    l2_b = block_l2_errors(p, rb, us, inside)
    assert max(l2_b) <= 1.2 * max(l2_i) + 1e-12


@pytest.mark.parametrize("name", sorted(FAMILY_FLOORS))
def test_block_per_family_l2_floor(name):
    r = case_by_name(name)
    out = __import__("poisson_tpu.geometry.manufactured",
                     fromlist=["manufactured_error"]).manufactured_error(
        r, 100, 150, dtype="float32", krylov=BLK)
    assert out["flags"] == [1, 1, 1], out
    assert out["rel"] <= FAMILY_FLOORS[name], out


@pytest.mark.parametrize("name", sorted(FAMILY_FLOORS))
def test_deflated_per_family_l2_floor_and_warm_win(name):
    from poisson_tpu.geometry.manufactured import manufactured_error

    out = manufactured_error(case_by_name(name), 100, 150,
                             dtype="float32", krylov=DEFL)
    assert out["flag"] == 1, out
    assert out["rel"] <= FAMILY_FLOORS[name], out
    assert out["iterations"] < out["cold_iterations"], out


def test_block_rejections_are_loud():
    p = Problem(M=40, N=40)
    g1 = Ellipse(cx=0.1, cy=0.0, rx=0.5, ry=0.3)
    g2 = Rectangle(-0.5, -0.3, 0.5, 0.3)
    with pytest.raises(ValueError, match="unknown mode"):
        solve_batched(p, rhs_gates=[1.0], mode="blk")
    with pytest.raises(ValueError, match="sharded"):
        solve_batched(p, rhs_gates=[1.0, 1.1], mode="block",
                      mesh=object())
    with pytest.raises(ValueError, match="integrity probe"):
        solve_batched(p, rhs_gates=[1.0, 1.1], mode="block",
                      verify_every=5)
    with pytest.raises(ValueError, match="jacobi"):
        solve_batched(p, rhs_gates=[1.0, 1.1], mode="block",
                      preconditioner="mg")
    with pytest.raises(ValueError, match="exact-size"):
        solve_batched(p, rhs_gates=[1.0, 1.1], mode="block", bucket=8)
    with pytest.raises(ValueError, match="ONE shared operator"):
        solve_batched(p, rhs_gates=[1.0, 1.1], mode="block",
                      geometries=[g1, g2])


def test_block_shared_geometry_and_bucket_key_family():
    """A fingerprint-uniform geometry block runs on the shared
    canvases, and block executables form their own bucket-cache key
    family (a block dispatch never claims reuse of the independent
    executable)."""
    p = Problem(M=40, N=40)
    g = Ellipse(cx=0.1, cy=0.0, rx=0.5, ry=0.3)
    solve_batched(p, rhs_gates=[1.0, 1.2], dtype="float32")
    assert metrics.get("batched.bucket_cache.misses") == 1
    r = solve_batched(p, rhs_gates=[1.0, 1.2], dtype="float32",
                      mode="block", geometries=[g, g])
    assert (np.asarray(r.flag) == FLAG_CONVERGED).all()
    # block dispatch = a NEW executable family, not a hit on the
    # independent one
    assert metrics.get("batched.bucket_cache.misses") == 2
    assert metrics.get("batched.bucket_cache.hits") == 0
    assert metrics.get("krylov.block.solves") == 2


# -- deflation recycling -------------------------------------------------

def test_recycle_warm_beats_cold_and_counts():
    p = Problem(M=60, N=60)
    cold = recycle.solve_recycled(p, dtype="float32", policy=DEFL)
    warm = recycle.solve_recycled(p, dtype="float32", policy=DEFL,
                                  rhs_gate=1.5)
    assert int(cold.flag) == FLAG_CONVERGED
    assert int(warm.flag) == FLAG_CONVERGED
    assert int(warm.iterations) < int(cold.iterations)
    assert metrics.get("krylov.cache.misses") == 1
    assert metrics.get("krylov.cache.hits") == 1
    assert metrics.get("krylov.harvests") == 1
    assert metrics.get("krylov.warm_solves") == 1
    assert metrics.get("krylov.iterations_saved") >= 1


def test_recycle_dtype_change_misses():
    """Escalation invalidation by construction: the basis key carries
    the dtype, so an f64 request after an f32 harvest re-harvests."""
    p = Problem(M=40, N=40)
    recycle.solve_recycled(p, dtype="float32", policy=DEFL)
    assert recycle.has_basis(p, dtype="float32", policy=DEFL)
    assert not recycle.has_basis(p, dtype="float64", policy=DEFL)
    recycle.solve_recycled(p, dtype="float64", policy=DEFL)
    assert metrics.get("krylov.cache.misses") == 2
    assert metrics.get("krylov.cache.hits") == 0


def test_recycle_eviction_respects_byte_budget():
    tiny = KrylovPolicy(deflation=True, harvest=16, keep=4,
                        budget_bytes=1)
    p = Problem(M=40, N=40)
    recycle.solve_recycled(p, dtype="float32", policy=tiny)
    recycle.solve_recycled(p, dtype="float32", policy=tiny,
                           geometry=Ellipse(cx=0.1, cy=0.0, rx=0.5,
                                            ry=0.3))
    # over-budget: the LRU keeps only the newest entry
    assert metrics.get("krylov.cache.evictions") >= 1
    assert recycle.cache_stats()["entries"] == 1


def test_recycle_poisoned_basis_falls_back_never_wrong():
    p = Problem(M=60, N=60)
    cold = recycle.solve_recycled(p, dtype="float32", policy=DEFL)
    assert recycle.poison_basis() == 1
    again = recycle.solve_recycled(p, dtype="float32", policy=DEFL,
                                   rhs_gate=0.8)
    assert int(again.flag) == FLAG_CONVERGED
    assert np.isfinite(np.asarray(again.w)).all()
    assert metrics.get("krylov.fallbacks") == 1
    assert metrics.get("krylov.cache.invalidations") == 1
    # the fallback cold solve re-harvested: the next request is warm
    warm = recycle.solve_recycled(p, dtype="float32", policy=DEFL,
                                  rhs_gate=1.2)
    assert int(warm.iterations) < int(cold.iterations)


def test_recycle_invalidate_selectors():
    p = Problem(M=40, N=40)
    g = Ellipse(cx=0.1, cy=0.0, rx=0.5, ry=0.3)
    recycle.solve_recycled(p, dtype="float32", policy=DEFL,
                           hw=("xla", "cpu", 0))
    recycle.solve_recycled(p, dtype="float32", policy=DEFL, geometry=g,
                           hw=("xla", "cpu", 1))
    assert recycle.cache_stats()["entries"] == 2
    # hw selector drops only the matching harvest cohort
    assert recycle.invalidate(hw=("xla", "cpu", 1), reason="test") == 1
    assert recycle.has_basis(p, dtype="float32", policy=DEFL)
    assert not recycle.has_basis(p, dtype="float32", policy=DEFL,
                                 geometry=g)
    # fingerprint selector
    assert recycle.invalidate(fingerprint="default", reason="test") == 1
    assert recycle.cache_stats()["entries"] == 0
    assert metrics.get("krylov.cache.invalidations") == 2


def test_recycle_unconverged_solve_never_caches():
    p = Problem(M=60, N=60, max_iter=5)     # cap far below convergence
    r = recycle.solve_recycled(p, dtype="float32", policy=DEFL)
    assert int(r.flag) != FLAG_CONVERGED
    assert metrics.get("krylov.harvests") == 0
    assert recycle.cache_stats()["entries"] == 0


def test_recycle_validation_loud():
    p = Problem(M=40, N=40)
    with pytest.raises(ValueError, match="deflation-enabled"):
        recycle.solve_recycled(p, policy=KrylovPolicy())
    with pytest.raises(ValueError, match="jacobi"):
        recycle.solve_recycled(p, policy=DEFL, preconditioner="mg")
    from poisson_tpu.geometry.manufactured import manufactured_error
    with pytest.raises(ValueError, match="jacobi"):
        manufactured_error(case_by_name("ellipse"), 40, 60,
                           krylov=DEFL, preconditioner="mg")


# -- serve threading -----------------------------------------------------

def _vc_service(policy=None, **kw):
    from poisson_tpu.serve import ServicePolicy, SolveService
    from poisson_tpu.testing.chaos import VirtualClock

    vc = VirtualClock()
    svc = SolveService(policy or ServicePolicy(capacity=16),
                       clock=vc, sleep=vc.sleep, seed=0, **kw)
    return svc, vc


def test_serve_cohort_markers():
    from poisson_tpu.serve import ServicePolicy, SolveRequest

    p = Problem(M=40, N=40)
    svc, _ = _vc_service()
    plain = SolveRequest(request_id=0, problem=p)
    assert svc._cohort(plain) == "40x40:auto:xla"     # historical string
    assert svc._cohort(SolveRequest(request_id=1, problem=p,
                                    krylov=BLK)) == "40x40:auto:xla:blk"
    assert svc._cohort(SolveRequest(request_id=2, problem=p,
                                    krylov=DEFL)) == "40x40:auto:xla:defl"
    g = Ellipse(cx=0.1, cy=0.0, rx=0.5, ry=0.3)
    assert svc._cohort(SolveRequest(
        request_id=3, problem=p, krylov=DEFL,
        geometry=g)) == "40x40:auto:xla:defl:geo"
    # policy-level default applies the marker service-wide
    svc2, _ = _vc_service(ServicePolicy(capacity=16, krylov=BLK))
    assert svc2._cohort(plain) == "40x40:auto:xla:blk"


def test_serve_block_batches_require_shared_operator():
    """Two block requests carrying DIFFERENT fingerprints share the
    :blk cohort but must never share a dispatch — batch formation is
    fingerprint-uniform for block heads."""
    from poisson_tpu.serve import ServicePolicy, SolveRequest

    dispatches = []

    def record(requests, attempts):
        dispatches.append([r.request_id for r in requests])

    svc, _ = _vc_service(
        ServicePolicy(capacity=16, max_batch=8, krylov=BLK),
        dispatch_fault=record)
    p = Problem(M=40, N=40)
    g1 = Ellipse(cx=0.1, cy=0.0, rx=0.5, ry=0.3)
    g2 = Rectangle(-0.5, -0.3, 0.5, 0.3)
    svc.submit(SolveRequest(request_id="a1", problem=p, geometry=g1))
    svc.submit(SolveRequest(request_id="a2", problem=p, geometry=g1,
                            rhs_gate=1.2))
    svc.submit(SolveRequest(request_id="b1", problem=p, geometry=g2))
    outs = svc.drain()
    assert all(o.converged for o in outs)
    comps = [set(d) for d in dispatches]
    assert {"a1", "a2"} in comps        # same fingerprint co-batched
    assert {"b1"} in comps              # different operator solo
    assert metrics.get("krylov.block.solves") == 3


def test_serve_deflation_warm_solves_and_sticky_routing():
    from poisson_tpu.serve import ServicePolicy, SolveRequest

    p = Problem(M=40, N=40)
    svc, _ = _vc_service(ServicePolicy(capacity=16, krylov=DEFL))
    for i in range(3):
        svc.submit(SolveRequest(request_id=i, problem=p,
                                rhs_gate=1.0 + i / 10))
    outs = {o.request_id: o for o in svc.drain()}
    assert all(o.converged for o in outs.values())
    assert outs[1].iterations < outs[0].iterations
    assert outs[2].iterations < outs[0].iterations
    assert metrics.get("krylov.warm_solves") == 2
    assert metrics.get("serve.krylov.sticky_hits") == 2


def test_serve_validation_loud():
    from poisson_tpu.serve import SolveRequest

    p = Problem(M=40, N=40)
    svc, _ = _vc_service()
    with pytest.raises(ValueError, match="unknown krylov mode"):
        svc.submit(SolveRequest(request_id="x", problem=p,
                                krylov=KrylovPolicy(mode="nope")))
    with pytest.raises(ValueError, match="does not compose"):
        svc.submit(SolveRequest(
            request_id="y", problem=p,
            krylov=KrylovPolicy(mode="block", deflation=True)))
    with pytest.raises(ValueError, match="chunked"):
        svc.submit(SolveRequest(request_id="z", problem=p, krylov=DEFL,
                                deadline_seconds=10.0))
    with pytest.raises(ValueError, match="jacobi"):
        svc.submit(SolveRequest(request_id="w", problem=p, krylov=DEFL,
                                preconditioner="mg"))
    assert svc.stats()["admitted"] == 0     # nothing entered the ledger


def test_journal_recovery_rebuilds_the_basis(tmp_path):
    """A recovered process REBUILDS the basis rather than trusting
    unreplayed device state: recovery invalidates the cache audibly,
    and the next request against the same fingerprint re-harvests."""
    from poisson_tpu.serve import (
        ServicePolicy,
        SolveJournal,
        SolveRequest,
        SolveService,
    )
    from poisson_tpu.testing.chaos import VirtualClock

    p = Problem(M=40, N=40)
    path = str(tmp_path / "serve.journal")
    vc = VirtualClock()
    policy = ServicePolicy(capacity=16, krylov=DEFL)
    j1 = SolveJournal(path, clock=vc)
    svc = SolveService(policy, clock=vc, sleep=vc.sleep, seed=0,
                       journal=j1)
    svc.submit(SolveRequest(request_id="r0", problem=p))
    assert svc.drain()[0].converged
    assert recycle.has_basis(p, policy=DEFL)
    j1.close()                              # the process "dies"

    j2 = SolveJournal(path, clock=vc)
    svc2 = SolveService.recover(j2, policy, clock=vc, sleep=vc.sleep,
                                seed=0)
    assert not recycle.has_basis(p, policy=DEFL)
    assert metrics.get("krylov.cache.invalidations") >= 1
    misses_before = metrics.get("krylov.cache.misses")
    svc2.submit(SolveRequest(request_id="r1", problem=p, rhs_gate=1.3))
    assert svc2.drain()[0].converged
    assert metrics.get("krylov.cache.misses") == misses_before + 1
    assert metrics.get("krylov.harvests") >= 2
    j2.close()


def test_verify_demand_suspends_krylov_audibly():
    """The SDC defense wins over Krylov acceleration: with an always-on
    integrity stride, block batches dispatch through the VERIFIED
    independent program and deflation requests through the verified
    chunked path — converged typed results, zero internal errors, the
    suspension counted (serve.krylov.verify_suspensions) — instead of
    either crashing (block + verify_every used to ValueError into
    non-retried internal errors) or silently running unverified on
    flip-suspect silicon."""
    from poisson_tpu.integrity.probe import IntegrityPolicy
    from poisson_tpu.serve import ServicePolicy, SolveRequest

    p = Problem(M=40, N=40)
    svc, _ = _vc_service(ServicePolicy(
        capacity=16, max_batch=4,
        integrity=IntegrityPolicy(verify_every=10)))
    svc.submit(SolveRequest(request_id="b0", problem=p, krylov=BLK))
    svc.submit(SolveRequest(request_id="b1", problem=p, krylov=BLK,
                            rhs_gate=1.2))
    svc.submit(SolveRequest(request_id="d0", problem=p, krylov=DEFL))
    outs = {o.request_id: o for o in svc.drain()}
    assert all(o.kind == "result" and o.converged
               for o in outs.values()), outs
    assert metrics.get("serve.errors") == 0
    assert metrics.get("serve.krylov.verify_suspensions") >= 2
    # nothing ran the unverified krylov programs
    assert metrics.get("krylov.block.solves") == 0
    assert metrics.get("krylov.cache.misses") == 0


def test_journal_replays_request_level_krylov(tmp_path):
    """A crashed request-level block/deflation knob re-dispatches
    through the SAME cohort after replay — the policy rides the
    journal (the basis never does)."""
    from poisson_tpu.serve import (
        ServicePolicy,
        SolveJournal,
        SolveRequest,
        SolveService,
        replay_journal,
    )
    from poisson_tpu.testing.chaos import VirtualClock

    p = Problem(M=40, N=40)
    path = str(tmp_path / "j")
    vc = VirtualClock()
    j = SolveJournal(path, clock=vc)
    svc = SolveService(ServicePolicy(capacity=8), clock=vc,
                       sleep=vc.sleep, journal=j)
    svc.submit(SolveRequest(request_id="k0", problem=p, krylov=DEFL))
    svc.submit(SolveRequest(request_id="k1", problem=p, krylov=BLK))
    j.close()                               # crash before dispatch
    rep = replay_journal(path)
    assert rep.torn_records == 0
    by_id = {pend.request.request_id: pend.request
             for pend in rep.pending}
    assert by_id["k0"].krylov == DEFL
    assert by_id["k1"].krylov == BLK


def test_chaos_deflation_stale_basis_green():
    from poisson_tpu.testing.chaos import run_scenario

    report = run_scenario("deflation-stale-basis", seed=0)
    assert report["ok"], report["checks"]
    assert report["invariant"]["lost"] == 0


# -- cost models & sentinel pins -----------------------------------------

def test_krylov_cost_models():
    from poisson_tpu.obs.costs import (
        analytic_iteration_cost,
        krylov_block_cost,
        krylov_deflated_cost,
    )

    base = analytic_iteration_cost(400, 600)
    blk = krylov_block_cost(400, 600, 8)
    assert blk["bytes"] > 8 * base["bytes"]          # coupling surcharge
    assert blk["bytes_per_member_iteration"] > base["bytes"]
    defl = krylov_deflated_cost(400, 600, 9)
    assert defl["bytes"] == pytest.approx(
        base["bytes"] + 18 * 401 * 601 * 4)
    assert metrics.snapshot()["gauges"][
        "cost.krylov.block_bytes_per_iter"] == blk["bytes"]
    assert metrics.snapshot()["gauges"][
        "cost.krylov.deflated_passes"] == defl["passes"]


def test_sentinel_lifts_krylov_detail_into_cohort():
    import benchmarks.regress as regress

    warm = {"metric": "serve.sustained_solves_per_sec", "value": 30.0,
            "detail": {"grid": [96, 144], "dtype": "float32",
                       "platform": "cpu", "backend": "xla_serve",
                       "devices": 1, "arrival_rate": 40.0,
                       "deflation": True, "repeat_fingerprint": 3,
                       "krylov_mode": "independent",
                       "fault_load": "clean"}}
    cold = {"metric": "serve.sustained_solves_per_sec", "value": 8.0,
            "detail": {"grid": [96, 144], "dtype": "float32",
                       "platform": "cpu", "backend": "xla_serve",
                       "devices": 1, "arrival_rate": 40.0,
                       "fault_load": "clean"}}
    rw = regress.record_from_result(warm, "warm")
    rc = regress.record_from_result(cold, "cold")
    assert rw["deflation"] is True and rw["repeat_fingerprint"] == 3
    assert regress.cohort_key(rw) != regress.cohort_key(rc)
    # a warm-dominated run never judges the cold baseline: evaluating
    # both together raises no alarm despite the 4x value gap
    verdict = regress.evaluate([rc, rc, rc, rw])
    assert not verdict["regressions"]
    # block A/B records split from the plain batched cohort the same way
    blk = regress.record_from_result(
        {"metric": "batched_solves_per_sec", "value": 1.0,
         "detail": {"grid": [400, 600], "dtype": "float32",
                    "platform": "cpu", "backend": "xla_batched",
                    "devices": 1, "krylov_mode": "block"}}, "blk")
    ind = regress.record_from_result(
        {"metric": "batched_solves_per_sec", "value": 5.0,
         "detail": {"grid": [400, 600], "dtype": "float32",
                    "platform": "cpu", "backend": "xla_batched",
                    "devices": 1}}, "ind")
    assert regress.cohort_key(blk) != regress.cohort_key(ind)


def test_manufactured_block_gate_shape():
    out = __import__("poisson_tpu.geometry.manufactured",
                     fromlist=["manufactured_error"]).manufactured_error(
        case_by_name("ellipse"), 60, 90, dtype="float32", krylov=BLK)
    assert set(out) >= {"case", "l2", "rel", "iterations", "flags",
                        "deficient"}
    assert len(cases()) == 8        # the floor table covers every family
    assert set(FAMILY_FLOORS) == {c.name for c in cases()}
