"""Convergence observatory: online iteration forecasting, the
predicted-deadline admission/preemption seam, and the fleet scoreboard
(tier-1, CPU-deterministic; -m forecast).

Four layers under test: the streaming estimator arithmetic
(:mod:`poisson_tpu.obs.forecast` — log-residual slopes, cold analytic
seeds, CRC-sealed snapshots), the opt-in ``history_every`` residual tap
and its flag-off byte-identity contract, the service-side
``ForecastPolicy`` lifecycle (typed ``predicted_deadline`` sheds with
ZERO compute burned, lane-boundary re-forecast preemption, ETA backlog
degradation), and the ``python -m poisson_tpu top`` scoreboard reading
the same numbers live or post-mortem. Timing-dependent behaviour runs
on an injected :class:`VirtualClock`, so every assertion is a pure
function of the campaign seed.
"""

import json
import math
import os
import sys

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.obs import forecast, metrics
from poisson_tpu.obs import flight
from poisson_tpu.serve import (
    ForecastPolicy,
    OUTCOME_SHED,
    SCHED_CONTINUOUS,
    SHED_PREDICTED_DEADLINE,
    DegradationPolicy,
    ServicePolicy,
    SolveJournal,
    SolveRequest,
    SolveService,
)
from poisson_tpu.testing.chaos import VirtualClock

pytestmark = pytest.mark.forecast

P40 = Problem(M=40, N=40)          # converges in 50 iterations (golden)


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    forecast.set_history(None)
    yield
    metrics.reset()
    forecast.set_history(None)


def _service(policy, **kw):
    vc = VirtualClock()
    svc = SolveService(policy, clock=vc, sleep=vc.sleep, **kw)
    return svc, vc


def _quiet_degradation():
    return DegradationPolicy(shrink_padding_at=9.0, cap_iterations_at=9.0,
                             downshift_precision_at=9.0)


# -- estimator arithmetic ------------------------------------------------


def test_log_residual_slope_recovers_geometric_decay():
    s = -0.3
    samples = [(k, 2.0 * math.exp(s * k)) for k in (5, 10, 15, 20, 25)]
    fit = forecast.log_residual_slope(samples)
    assert fit == pytest.approx(s, rel=1e-9)


def test_log_residual_slope_unfittable_cases():
    assert forecast.log_residual_slope([]) is None
    assert forecast.log_residual_slope([(10, 1e-3)]) is None
    # non-positive residuals are unusable in log space and are dropped
    assert forecast.log_residual_slope([(5, 0.0), (10, -1.0)]) is None
    # identical abscissae: zero variance in k, no fit
    assert forecast.log_residual_slope([(7, 1e-2), (7, 1e-3)]) is None


def test_remaining_iterations_closed_form():
    slope = -0.2
    diff, delta = 1e-2, 1e-6
    rem = forecast.remaining_iterations(diff, delta, slope)
    assert rem == math.ceil(math.log(delta / diff) / slope)
    # already converged: nothing remaining
    assert forecast.remaining_iterations(1e-8, 1e-6, slope) == 0


def test_remaining_iterations_never_guesses():
    # unknown or non-contracting slope must never predict (a blind
    # preemption would be worse than a deadline partial)
    assert forecast.remaining_iterations(1e-2, 1e-6, None) is None
    assert forecast.remaining_iterations(1e-2, 1e-6, 0.0) is None
    assert forecast.remaining_iterations(1e-2, 1e-6, 0.1) is None
    assert forecast.remaining_iterations(0.0, 1e-6, -0.1) is None
    assert forecast.remaining_iterations(1e-2, 0.0, -0.1) is None


def test_progress_fraction_clamps():
    assert forecast.progress_fraction(0, 100) == 0.0
    assert forecast.progress_fraction(50, 100) == pytest.approx(0.5)
    assert forecast.progress_fraction(140, 100) == 1.0
    assert forecast.progress_fraction(5, 0) == 0.0


def test_cold_seeds_scale_with_the_grid():
    # sqrt(M*N): the O(n) Jacobi-PCG iteration law on an n-by-n grid
    assert forecast.cold_iterations(40, 40) == 40
    assert forecast.cold_iterations(20, 24) == round(math.sqrt(480))
    small = forecast.cold_seconds_per_iteration(40, 40)
    big = forecast.cold_seconds_per_iteration(400, 600)
    assert 0.0 < small < big
    # f32 halves the bytes moved per sweep
    f32 = forecast.cold_seconds_per_iteration(40, 40, dtype_bytes=4)
    assert f32 < small


def test_quantile_nearest_rank():
    vals = [1.0, 2.0, 3.0, 4.0, 10.0]
    assert forecast._quantile(vals, 0.5) == 3.0
    assert forecast._quantile(vals, 0.9) == 10.0
    assert forecast._quantile([7.0], 0.5) == 7.0


def test_history_buffer_capture_and_slope():
    buf = forecast.HistoryBuffer()
    for k in (5, 10, 15):
        buf.emit(k, math.exp(-0.1 * k))
    assert buf.slope() == pytest.approx(-0.1, rel=1e-9)
    prev = forecast.set_history(buf)
    assert prev is None and forecast.get_history() is buf
    forecast.history_tap(20, math.exp(-2.0))
    assert len(buf.samples) == 4
    forecast.set_history(None)
    forecast.history_tap(25, 1e-3)      # sink detached: a silent no-op
    assert len(buf.samples) == 4


# -- snapshot persistence ------------------------------------------------


def test_snapshot_roundtrip_preserves_calibration(tmp_path):
    path = str(tmp_path / "journal.forecast.json")
    model = forecast.ForecastModel()
    for it in (48, 50, 52, 50):
        model.predict("c", M=40, N=40)
        model.observe("c", it, 0.01, M=40, N=40)
    assert model.save(path) and os.path.exists(path)
    warm = forecast.ForecastModel()
    assert warm.load(path) is True
    fc = warm.predict("c", M=40, N=40)
    assert fc.cold is False and fc.samples == 4
    assert fc.iterations_p50 == \
        model.predict("c", M=40, N=40).iterations_p50
    assert metrics.get("obs.forecast.snapshot.saves") == 1
    assert metrics.get("obs.forecast.snapshot.loads") == 1


def test_torn_snapshot_is_audible_and_falls_back_cold(tmp_path):
    path = str(tmp_path / "journal.forecast.json")
    model = forecast.ForecastModel()
    model.observe("c", 50, 0.01, M=40, N=40)
    assert model.save(path)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 2])        # torn write
    warm = forecast.ForecastModel()
    assert warm.load(path) is False
    assert metrics.get("obs.forecast.snapshot.torn") == 1
    assert warm.predict("c", M=40, N=40).cold is True
    # a flipped byte (CRC mismatch, valid JSON) is equally audible
    sealed = json.loads(raw)
    sealed["crc32"] = (sealed["crc32"] + 1) % (1 << 32)
    open(path, "w").write(json.dumps(sealed))
    assert forecast.ForecastModel().load(path) is False
    assert metrics.get("obs.forecast.snapshot.torn") == 2


def test_missing_snapshot_is_silent(tmp_path):
    model = forecast.ForecastModel()
    assert model.load(str(tmp_path / "absent.json")) is False
    assert metrics.get("obs.forecast.snapshot.torn") == 0


# -- the history tap's byte-identity contract ---------------------------


def test_history_flag_off_program_is_byte_identical_to_ledger():
    """``history_every=0`` must lower to the committed flag-off
    executable bit-for-bit, and ``history_every=5`` must match ITS
    committed opt-in entry (callbacks legal there, still no
    collectives) — the ledger pins both sides of the seam."""
    from poisson_tpu.contracts.hlo import find_forbidden, hlo_fingerprint
    from poisson_tpu.contracts.manifest import (_problem, _setup,
                                                load_ledger, markers_for)
    from poisson_tpu.solvers.pcg import _solve

    entries = load_ledger()["entries"]
    a, b, rhs, aux = _setup("float64", False)
    off = _solve.lower(_problem(), False, 0, 0, 0.0, False, 0,
                       a, b, rhs, aux).as_text()
    assert not find_forbidden(off, markers_for(("callbacks",)))
    assert hlo_fingerprint(off) == \
        entries["solve.jacobi_f64"]["fingerprint"]
    on = _solve.lower(_problem(), False, 0, 0, 0.0, False, 5,
                      a, b, rhs, aux).as_text()
    assert find_forbidden(on, markers_for(("callbacks",)))
    assert not find_forbidden(on, markers_for(("collectives", "mg")))
    assert hlo_fingerprint(on) == \
        entries["solve.history_f64"]["fingerprint"]


def test_history_tap_does_not_change_convergence():
    """Golden-count pin: the residual-history callback observes, never
    perturbs — iterations, final diff, and the solution field are
    bit-for-bit across history off/on, and the tap captured exactly
    the k % 5 == 0 boundaries."""
    from poisson_tpu.solvers.pcg import pcg_solve

    base = pcg_solve(P40, dtype="float64", scaled=False)
    buf = forecast.HistoryBuffer()
    forecast.set_history(buf)
    tapped = pcg_solve(P40, dtype="float64", scaled=False,
                       history_every=5)
    forecast.set_history(None)
    assert tapped.iterations == base.iterations
    assert float(tapped.diff) == float(base.diff)
    np.testing.assert_array_equal(np.asarray(tapped.w),
                                  np.asarray(base.w))
    ks = [k for k, _ in buf.samples]
    assert ks and all(k % 5 == 0 for k in ks)
    assert buf.slope() is not None and buf.slope() < 0


def test_history_rejects_the_mg_path():
    from poisson_tpu.mg.hierarchy import MGConfig
    from poisson_tpu.solvers.pcg import pcg_solve

    with pytest.raises(ValueError, match="history_every"):
        pcg_solve(P40, preconditioner="mg", mg_config=MGConfig(),
                  history_every=5)


# -- predicted-deadline admission (both engines) ------------------------


@pytest.mark.parametrize("scheduling", ["drain", SCHED_CONTINUOUS])
def test_doomed_deadline_sheds_typed_with_zero_compute(scheduling):
    """The acceptance criterion: after the cohort calibrates, a
    deadline the model prices as hopeless is refused AT ADMISSION —
    typed ``shed[predicted_deadline]``, no dispatch, no iterations —
    and the ledger still closes (nothing lost), under both engines."""
    svc, _ = _service(ServicePolicy(
        capacity=16, scheduling=scheduling,
        degradation=_quiet_degradation(),
        forecast=ForecastPolicy()))
    for i in range(3):
        svc.submit(SolveRequest(request_id=f"warm-{i}", problem=P40))
    warm = svc.drain()
    assert all(o.converged for o in warm)
    doomed = svc.submit(SolveRequest(request_id="doomed", problem=P40,
                                     deadline_seconds=1e-9))
    assert doomed is not None and doomed.kind == OUTCOME_SHED
    assert doomed.shed_reason == SHED_PREDICTED_DEADLINE
    d = doomed.decomposition or {}
    assert d.get("compute_s", 1) == 0
    assert d.get("dispatches", 1) == 0
    assert d.get("iterations", 1) == 0
    assert metrics.get("serve.shed.predicted_deadline") == 1
    assert metrics.get("serve.forecast.admission_checks") == 1
    stats = svc.stats()
    assert stats["lost"] == 0 and stats["pending"] == 0


def test_feasible_deadline_still_admits_on_a_warm_cohort():
    svc, _ = _service(ServicePolicy(
        capacity=16, degradation=_quiet_degradation(),
        forecast=ForecastPolicy()))
    for i in range(3):
        svc.submit(SolveRequest(request_id=f"warm-{i}", problem=P40))
    svc.drain()
    assert svc.submit(SolveRequest(request_id="ok", problem=P40,
                                   deadline_seconds=3600.0)) is None
    (out,) = svc.drain()
    assert out.converged and out.request_id == "ok"
    assert metrics.get("serve.shed.predicted_deadline") == 0


def test_no_deadline_request_is_never_admission_checked():
    svc, _ = _service(ServicePolicy(
        capacity=16, degradation=_quiet_degradation(),
        forecast=ForecastPolicy()))
    svc.submit(SolveRequest(request_id="free", problem=P40))
    svc.drain()
    assert metrics.get("serve.forecast.admission_checks") == 0


def test_forecast_off_by_default_no_observatory_traffic():
    assert ServicePolicy().forecast is None
    svc, _ = _service(ServicePolicy(capacity=16))
    svc.submit(SolveRequest(request_id="r", problem=P40,
                            deadline_seconds=1e-9))
    svc.drain()
    assert metrics.get("obs.forecast.predictions") == 0
    assert metrics.get("serve.forecast.admission_checks") == 0


def test_forecast_policy_defaults():
    fp = ForecastPolicy()
    assert fp.admission_shed and fp.reforecast
    assert not fp.backlog_degradation
    assert fp.margin == 1.0 and fp.history_every == 0


# -- lane-boundary re-forecast preemption -------------------------------


def test_reforecast_preempts_a_doomed_lane_occupant():
    """Admission let an optimistic deadline through
    (``admission_shed=False``); the continuous engine's lane-boundary
    re-forecast — fit to the request's OWN residual history — prices
    the remaining work above the deadline budget (margin inflated to
    force the verdict deterministically) and pre-empts mid-flight:
    a typed predicted-deadline shed plus ``serve.forecast.preempted``,
    with the breaker never blamed."""
    svc, _ = _service(ServicePolicy(
        capacity=8, scheduling=SCHED_CONTINUOUS, refill_chunk=10,
        degradation=_quiet_degradation(),
        forecast=ForecastPolicy(admission_shed=False, reforecast=True,
                                margin=1e6)))
    svc.submit(SolveRequest(request_id="victim", problem=P40,
                            deadline_seconds=5.0))
    (out,) = svc.drain()
    assert out.kind == OUTCOME_SHED
    assert out.shed_reason == SHED_PREDICTED_DEADLINE
    assert metrics.get("serve.forecast.preempted") == 1
    assert svc.stats()["lost"] == 0


def test_reforecast_never_preempts_without_a_fitted_slope():
    """One lane boundary = one history point = no slope: the re-forecast
    must decline to guess, and the request runs to convergence (margin
    would otherwise doom it instantly)."""
    svc, _ = _service(ServicePolicy(
        capacity=8, scheduling=SCHED_CONTINUOUS, refill_chunk=100,
        degradation=_quiet_degradation(),
        forecast=ForecastPolicy(admission_shed=False, reforecast=True,
                                margin=1e6)))
    svc.submit(SolveRequest(request_id="r", problem=P40,
                            deadline_seconds=5.0))
    (out,) = svc.drain()
    assert out.converged
    assert metrics.get("serve.forecast.preempted") == 0


# -- ETA backlog degradation --------------------------------------------


def test_backlog_degradation_rung_fires_and_is_counted():
    svc, _ = _service(ServicePolicy(
        capacity=32, degradation=_quiet_degradation(),
        forecast=ForecastPolicy(backlog_degradation=True,
                                backlog_objective_seconds=1e-9)))
    for i in range(6):
        svc.submit(SolveRequest(request_id=i, problem=P40))
    outs = svc.drain()
    assert len(outs) == 6 and svc.stats()["lost"] == 0
    assert metrics.get("serve.degraded.backlog_driven") >= 1


def test_backlog_gauge_published():
    svc, _ = _service(ServicePolicy(
        capacity=16, degradation=_quiet_degradation(),
        forecast=ForecastPolicy()))
    svc.submit(SolveRequest(request_id="r", problem=P40))
    svc.drain()
    snap = metrics.snapshot()
    assert "serve.forecast.backlog_seconds" in snap["gauges"]


# -- calibration --------------------------------------------------------


def test_calibration_error_bounded_on_repeat_traffic():
    """The ≤25% p50 acceptance bound: on a warm repeating cohort the
    forecaster's median absolute iteration error collapses (identical
    problems iterate identically)."""
    svc, _ = _service(ServicePolicy(
        capacity=32, degradation=_quiet_degradation(),
        forecast=ForecastPolicy()))
    for i in range(6):
        svc.submit(SolveRequest(request_id=i, problem=P40))
    svc.drain()
    err = svc._forecast.calibration_err_pct()
    assert err is not None and err <= 25.0
    assert metrics.get("obs.forecast.predictions") >= 6
    assert metrics.get("obs.forecast.cold_cohorts") == 1


def test_session_snapshot_warm_loads_on_recover(tmp_path):
    """Journal-attached services persist the model beside the journal
    and a recovered service loads it: the first post-crash prediction
    is already calibrated (no cold re-seeding across restarts)."""
    jpath = str(tmp_path / "serve.journal")
    policy = ServicePolicy(capacity=16,
                           degradation=_quiet_degradation(),
                           forecast=ForecastPolicy())
    vc0 = VirtualClock()
    svc = SolveService(policy, clock=vc0, sleep=vc0.sleep,
                       journal=SolveJournal(jpath, clock=vc0))
    for i in range(3):
        svc.submit(SolveRequest(request_id=f"w{i}", problem=P40))
    svc.drain()
    assert os.path.exists(forecast.snapshot_path(jpath))
    vc = VirtualClock()
    revived = SolveService.recover(SolveJournal(jpath, clock=vc),
                                   policy, clock=vc, sleep=vc.sleep)
    fc = revived._forecast.predict(
        svc._cohort(SolveRequest(request_id="x", problem=P40)),
        **svc._forecast_args(SolveRequest(request_id="x", problem=P40)))
    assert fc.cold is False and fc.samples >= 3


# -- flight-recorder annotation (satellite: per-member dk attrs) --------


def test_annotate_rides_the_open_span(tmp_path):
    from poisson_tpu import obs
    from poisson_tpu.obs.trace import load_events

    obs.configure(trace_dir=str(tmp_path))
    vc = VirtualClock()
    fr = flight.FlightRecorder(clock=vc)
    fr.admit("r")
    fr.begin("r", flight.SPAN_RESIDENT)
    fr.annotate("r", flight.SPAN_RESIDENT, dk=12, k=24)
    fr.annotate("r", flight.SPAN_RESIDENT, k=36)     # later values win
    fr.annotate("r", "not_open", x=1)                # silent no-op
    vc.advance(0.5)
    fr.end("r", flight.SPAN_RESIDENT)
    obs.finalize()
    (span,) = [e for e in load_events(str(tmp_path))
               if e.get("name") == "flight.span"]
    assert span["attrs"]["dk"] == 12 and span["attrs"]["k"] == 36


# -- regression-sentinel & chaos pins -----------------------------------


def test_calibration_metric_pinned_lower_is_better():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "benchmarks"))
    try:
        import regress
    finally:
        sys.path.pop(0)
    assert "obs.forecast.calibration_err_pct" in regress._METRICS
    assert "obs.forecast.calibration_err_pct" in regress._LOWER_IS_BETTER
    rec = {"metric": "serve.p99_latency", "value": 0.5,
           "detail": {"grid": [40, 40], "dtype": "float32",
                      "platform": "cpu", "backend": "xla_serve",
                      "devices": 1,
                      "forecast_calibration_err_pct": 3.2}}
    recs = regress.records_from_result(rec, "r.json")
    assert [r["metric"] for r in recs] == \
        ["serve.p99_latency", "obs.forecast.calibration_err_pct"]
    assert regress.cohort_key(recs[0]) != regress.cohort_key(recs[1])
    del rec["detail"]["forecast_calibration_err_pct"]
    assert len(regress.records_from_result(rec, "r.json")) == 1


def test_chaos_scenario_registered_and_green():
    from poisson_tpu.testing import chaos

    assert "forecast-predicted-shed" in chaos.scenario_names()
    report = chaos.run_scenario("forecast-predicted-shed", seed=0)
    assert report["ok"], report["checks"]
    assert report["checks"]["zero_compute_burned"]
    assert report["checks"]["feasible_twin_still_served"]


# -- the scoreboard -----------------------------------------------------


def _run_some_forecast_traffic():
    svc, _ = _service(ServicePolicy(
        capacity=16, degradation=_quiet_degradation(),
        forecast=ForecastPolicy()))
    for i in range(3):
        svc.submit(SolveRequest(request_id=i, problem=P40))
    svc.drain()
    svc.submit(SolveRequest(request_id="doomed", problem=P40,
                            deadline_seconds=1e-9))


def test_scoreboard_agrees_across_both_sources():
    """The same numbers whether read from a live registry snapshot or
    round-tripped through the Prometheus exposition — the scoreboard
    must not depend on which side of the wire it runs."""
    from poisson_tpu.obs import export

    _run_some_forecast_traffic()
    snap = metrics.snapshot()
    live = forecast.build_scoreboard(snap)
    wire = forecast.build_scoreboard(export.parse_text(
        export.render(snap)))
    assert live["forecast"] == wire["forecast"]
    assert live["queue"] == wire["queue"]
    assert live["forecast"]["predictions"] >= 3
    assert live["forecast"]["predicted_deadline_sheds"] == 1
    text = forecast.render_scoreboard(live)
    assert "forecast" in text and "p50_err" in text


def test_top_cli_post_mortem_metrics_dir(tmp_path, capsys):
    from poisson_tpu.cli import _main_top

    _run_some_forecast_traffic()
    (tmp_path / "metrics-rank0.json").write_text(
        json.dumps(metrics.snapshot(rank=0)))
    rc = _main_top(["--metrics-dir", str(tmp_path), "--json"])
    assert rc == 0
    board = json.loads(capsys.readouterr().out)
    assert board["forecast"]["predictions"] >= 3
    assert board["forecast"]["predicted_deadline_sheds"] == 1


def test_top_cli_source_validation(tmp_path, capsys):
    from poisson_tpu.cli import _main_top

    assert _main_top(["--json"]) == 2                  # no source
    assert _main_top(["--metrics-dir", str(tmp_path), "--textfile",
                      str(tmp_path / "x.prom"), "--json"]) == 2
    capsys.readouterr()
    assert _main_top(["--textfile", str(tmp_path / "absent.prom"),
                      "--json"]) == 1                  # unreadable


def test_top_cli_textfile_source(tmp_path, capsys):
    from poisson_tpu.cli import _main_top
    from poisson_tpu.obs import export

    _run_some_forecast_traffic()
    path = tmp_path / "metrics.prom"
    export.write_textfile(str(path))
    rc = _main_top(["--textfile", str(path), "--json"])
    assert rc == 0
    board = json.loads(capsys.readouterr().out)
    assert board["forecast"]["predicted_deadline_sheds"] == 1
