"""Durable solver sessions (`poisson_tpu.serve.session` +
`poisson_tpu.solvers.session`): crash-safe moving-domain streams with a
warm-start validity gate (tier-1, CPU-deterministic; -m session).

The acceptance surface:

- the COLD step path is the literal historical solve: the ledgered
  ``session.step_cold_f64`` lowering is byte-identical (fingerprint) to
  ``solve.jacobi_f64``;
- a valid warm start cuts iterations; a stale one (family change,
  drift past the bound, nonsense residual) falls back cold AUDIBLY —
  counted, reasoned, never silent;
- every step transition is journaled, so a recovery replays to the
  exact committed step boundary with the ledger invariant closed and
  NO warm iterate (device state died with the process);
- one causal flight tree per session, complete from the emitted JSONL;
- implicit-Euler heat steps contract to the Poisson steady state;
- the seeded session chaos scenarios hold their invariants;
- the regression sentinel splits session records into their own cohort
  and keeps the throughput direction pin (a drop alarms).
"""

import numpy as np
import pytest

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.geometry import Ellipse, Rectangle
from poisson_tpu.obs import flight, metrics
from poisson_tpu.obs.trace import load_events
from poisson_tpu.serve import (
    OUTCOME_RESULT,
    ServicePolicy,
    SessionHost,
    SessionPolicy,
    SolveJournal,
    SolveRequest,
    SolveService,
    replay_sessions,
)
from poisson_tpu.solvers.pcg import FLAG_CONVERGED, pcg_solve
from poisson_tpu.solvers.session import (
    reset_session_cache,
    session_step_solve,
    warm_validity,
)
from poisson_tpu.testing import chaos

pytestmark = pytest.mark.session

P32 = Problem(M=32, N=32)


@pytest.fixture(autouse=True)
def _fresh_registries():
    obs.shutdown()
    metrics.reset()
    reset_session_cache()
    yield
    obs.shutdown()
    metrics.reset()
    reset_session_cache()


def _host(policy=None, session=None, **kw):
    svc = SolveService(policy or ServicePolicy(capacity=32,
                                               session=session
                                               or SessionPolicy()), **kw)
    return SessionHost(svc), svc


# -- cold-path bit-discipline (HLO pin) --------------------------------


def test_cold_session_path_is_the_historical_solve_byte_identical():
    """The ledger pin that makes warm starts safe to ship: a session
    step with no (valid) warm iterate lowers to the EXACT historical
    jacobi program — same fingerprint, not merely same results."""
    from poisson_tpu.contracts import manifest

    by_name = {s.name: s for s in manifest.PROGRAMS}
    assert "session.step_cold_f64" in by_name
    assert "session.warm_f64" in by_name
    cold = manifest.hlo_fingerprint(
        manifest.lower_program(by_name["session.step_cold_f64"]))
    hist = manifest.hlo_fingerprint(
        manifest.lower_program(by_name["solve.jacobi_f64"]))
    assert cold == hist
    warm = manifest.hlo_fingerprint(
        manifest.lower_program(by_name["session.warm_f64"]))
    assert warm != cold  # the warm program is a DIFFERENT executable


# -- warm-start gate ----------------------------------------------------


def test_warm_validity_reasons():
    e = Ellipse(cx=0.1)
    assert warm_validity(e, Ellipse(cx=0.1)) == (True, "")
    assert warm_validity(e, Ellipse(cx=0.12)) == (True, "")
    ok, why = warm_validity(e, Ellipse(cx=0.9))
    assert not ok and why == "drift"
    ok, why = warm_validity(Rectangle(x0=-0.5, y0=-0.3, x1=0.5, y1=0.3), e)
    assert not ok and why == "family"
    ok, why = warm_validity(None, e)
    assert not ok and why == "family"


def test_valid_warm_start_cuts_iterations_and_counts_hits():
    spec = Ellipse()
    cold, info = session_step_solve(P32, geometry=spec)
    assert not info["warm_used"] and int(cold.flag) == FLAG_CONVERGED
    w = np.asarray(cold.w)
    warm, info = session_step_solve(
        P32, geometry=Ellipse(cx=5e-4), warm=w, warm_geometry=spec)
    assert info["warm_used"] and info["fallback"] == ""
    assert int(warm.flag) == FLAG_CONVERGED
    assert int(warm.iterations) < int(cold.iterations)
    assert metrics.get("session.warm.hits") == 1
    assert metrics.get("session.warm.fallbacks") == 0
    # warm and cold agree to solver tolerance on the same domain
    again, _ = session_step_solve(P32, geometry=spec, warm=w,
                                  warm_geometry=spec)
    assert np.allclose(np.asarray(again.w), w, atol=1e-5)


@pytest.mark.parametrize("stale, reason", [
    (dict(warm_geometry=Ellipse(cx=0.9)), "drift"),
    (dict(warm_geometry=Rectangle(x0=-0.5, y0=-0.3, x1=0.5, y1=0.3)),
     "family"),
    (dict(warm_geometry=Ellipse(), garbage=True), "residual"),
])
def test_stale_warm_start_falls_back_cold_audibly(stale, reason):
    spec = Ellipse()
    cold, _ = session_step_solve(P32, geometry=spec)
    w = np.asarray(cold.w)
    if stale.pop("garbage", False):
        # a checkerboard at 1e12: in-bounds drift, absurd residual
        i, j = np.indices(w.shape)
        w = np.where((i + j) % 2 == 0, 1e12, -1e12).astype(w.dtype)
    before = metrics.get("session.warm.fallbacks")
    result, info = session_step_solve(P32, geometry=spec, warm=w,
                                      **stale)
    assert not info["warm_used"] and info["fallback"] == reason
    # the fallback solve still answers
    assert int(result.flag) == FLAG_CONVERGED
    assert metrics.get("session.warm.fallbacks") == before + 1
    # a deliberately cold step (no warm offered) is NOT a fallback
    session_step_solve(P32, geometry=spec)
    assert metrics.get("session.warm.fallbacks") == before + 1


# -- the hosted stream --------------------------------------------------


def test_session_stream_warm_chain_through_the_service():
    host, svc = _host()
    sess = host.open("stream", P32, geometry=Ellipse())
    assert sess is not None
    outs = [host.step(sess, geometry=Ellipse(cx=5e-4 * k))
            for k in range(4)]
    assert all(o.kind == OUTCOME_RESULT for o in outs)
    assert metrics.get("session.warm.hits") >= 3
    assert int(outs[-1].iterations) < int(outs[0].iterations)
    summary = host.close(sess)
    assert summary["errors"] == 0 and summary["steps"] == 4
    # ledger invariant: session root + 4 steps, all typed
    snap = metrics.snapshot()["counters"]
    admitted = snap.get("serve.admitted", 0)
    done = (snap.get("serve.completed", 0) + snap.get("serve.errors", 0)
            + snap.get("serve.shed", 0))
    assert admitted == 5 and done == admitted


def test_new_sessions_shed_before_steps_of_inflight_ones():
    host, svc = _host(session=SessionPolicy(max_sessions=1))
    first = host.open("first", P32, geometry=Ellipse())
    assert first is not None
    second = host.open("second", P32, geometry=Ellipse())
    assert second is None  # shed, typed, audible
    assert metrics.get("serve.session.shed_opens") == 1
    # the in-flight stream keeps stepping
    out = host.step(first, geometry=Ellipse())
    assert out.kind == OUTCOME_RESULT
    host.close(first)


def test_session_fields_require_session_semantics_at_admission():
    svc = SolveService(ServicePolicy(capacity=8))
    with pytest.raises(ValueError, match="require session_id"):
        svc.submit(SolveRequest(request_id="r", problem=P32,
                                warm_start=np.zeros((33, 33))))
    with pytest.raises(ValueError, match="require session_id"):
        svc.submit(SolveRequest(request_id="r", problem=P32,
                                mass_shift=2.0))
    with pytest.raises(ValueError, match="fused jacobi session"):
        svc.submit(SolveRequest(request_id="r", problem=P32,
                                session_id="s", session_step=0,
                                preconditioner="mg"))
    with pytest.raises(ValueError, match="drop chunk"):
        svc.submit(SolveRequest(request_id="r", problem=P32,
                                session_id="s", session_step=0,
                                chunk=16))


# -- implicit-Euler heat stream -----------------------------------------


def test_heat_steps_contract_to_the_poisson_steady_state():
    spec = Ellipse()
    steady = np.asarray(pcg_solve(P32, geometry=spec).w)
    host, svc = _host()
    sess = host.open("heat", P32, kind="heat", mass_shift=1.0,
                     geometry=spec)
    errs = []
    for _ in range(6):
        out = host.step(sess)
        assert out.kind == OUTCOME_RESULT
        errs.append(float(np.linalg.norm(
            np.asarray(sess.warm) - steady)))
    host.close(sess)
    # monotone contraction onto the steady state, and close by the end
    assert all(b < a for a, b in zip(errs, errs[1:]))
    assert errs[-1] < 2e-2 * errs[0]


# -- journal replay & crash recovery ------------------------------------


def test_recovery_replays_to_the_committed_step_boundary(tmp_path):
    jpath = str(tmp_path / "session.journal")
    host, svc = _host(
        policy=ServicePolicy(capacity=32, session=SessionPolicy()),
        journal=SolveJournal(jpath), seed=0)
    sess = host.open("crashy", P32, geometry=Ellipse())
    for k in range(3):
        out = host.step(sess, geometry=Ellipse(cx=5e-4 * k))
        assert out.kind == OUTCOME_RESULT
    del host, svc  # the "crash": process memory (warm iterate) is gone

    rep = replay_sessions(jpath)["crashy"]
    # steps_submitted is the highest step INDEX the journal saw
    assert rep.last_advanced == 2 and rep.steps_submitted == 2
    assert not rep.closed

    svc2 = SolveService.recover(SolveJournal(jpath),
                                ServicePolicy(capacity=32), seed=0)
    host2 = SessionHost(svc2)
    recovered = host2.recover()
    assert [s.session_id for s in recovered] == ["crashy"]
    s2 = recovered[0]
    assert s2.next_step == 3          # continue AFTER the boundary
    assert s2.generation == 2
    assert s2.warm is None            # never resumed from dead state
    assert metrics.get("session.recovered") == 1
    before = metrics.get("session.warm.fallbacks")
    out = host2.step(s2, geometry=Ellipse(cx=5e-4 * 3))
    assert out.kind == OUTCOME_RESULT
    # the first post-recovery step ran COLD (no warm was offered, so
    # no fallback was counted either — cold by construction, not gate)
    assert metrics.get("session.warm.fallbacks") == before
    summary = host2.close(s2)
    assert summary["errors"] == 0 and summary["steps"] == 4


def test_second_crash_bumps_the_generation_again(tmp_path):
    jpath = str(tmp_path / "session.journal")
    host, svc = _host(
        policy=ServicePolicy(capacity=32, session=SessionPolicy()),
        journal=SolveJournal(jpath), seed=0)
    sess = host.open("twice", P32, geometry=Ellipse())
    host.step(sess)
    del host, svc
    svc2 = SolveService.recover(SolveJournal(jpath),
                                ServicePolicy(capacity=32), seed=0)
    h2 = SessionHost(svc2)
    (s2,) = h2.recover()
    h2.step(s2)
    del h2, svc2
    svc3 = SolveService.recover(SolveJournal(jpath),
                                ServicePolicy(capacity=32), seed=0)
    h3 = SessionHost(svc3)
    (s3,) = h3.recover()
    assert s3.generation == 3 and s3.next_step == 2
    out = h3.step(s3)
    assert out.kind == OUTCOME_RESULT
    h3.close(s3)


# -- one causal tree per session ----------------------------------------


def test_session_flight_trace_is_one_complete_tree(tmp_path):
    obs.configure(trace_dir=str(tmp_path))
    host, svc = _host()
    sess = host.open("traced", P32, geometry=Ellipse())
    for k in range(3):
        host.step(sess, geometry=Ellipse(cx=5e-4 * k))
    summary = host.close(sess)
    obs.finalize()
    events = load_events(str(tmp_path))
    report = flight.validate_events(events)
    assert report["complete"], report["problems"]
    tid, recs = flight.find_trace(events, trace_id=summary["trace_id"])
    assert tid is not None
    assert flight.validate_trace(recs) == []
    points = [r for r in recs
              if r.get("point") == flight.POINT_SESSION_STEP]
    assert [p.get("step") for p in points] == [0, 1, 2]
    assert summary["decomposition"]["wall_s"] >= 0.0


# -- chaos invariants ---------------------------------------------------


def test_session_chaos_scenarios_are_registered():
    names = chaos.scenario_names()
    for required in ("session-kill-recover-subprocess",
                     "session-stale-warm-start",
                     "session-device-loss-reroute"):
        assert required in names


def test_chaos_stale_warm_start_invariants():
    report = chaos.run_scenario("session-stale-warm-start", seed=0)
    assert report["ok"], report
    assert report["invariant"]["lost"] == 0


def test_chaos_device_loss_reroute_invariants():
    report = chaos.run_scenario("session-device-loss-reroute", seed=0)
    assert report["ok"], report
    assert report["invariant"]["lost"] == 0


# -- regression-sentinel cohort pins ------------------------------------


def test_sentinel_splits_session_records_into_their_own_cohort():
    import benchmarks.regress as regress

    base = {"grid": [300, 450], "dtype": "float64", "platform": "cpu",
            "backend": "xla_session", "devices": 1}
    sess = {"metric": "session.steps_per_sec", "value": 32.0,
            "detail": dict(base, session=True, warm_start=True)}
    cold = {"metric": "session.steps_per_sec", "value": 4.0,
            "detail": dict(base)}
    rs = regress.record_from_result(sess, "s")
    rc = regress.record_from_result(cold, "c")
    assert rs["session"] is True and rs["warm_start"] is True
    assert regress.cohort_key(rs) != regress.cohort_key(rc)
    # mixed cohorts never judge each other despite the 8x gap
    verdict = regress.evaluate([rc, rc, rc, rs])
    assert not verdict["regressions"]


def test_sentinel_direction_pin_a_throughput_drop_alarms():
    import benchmarks.regress as regress

    def rec(value, source):
        return regress.record_from_result(
            {"metric": "session.steps_per_sec", "value": value,
             "detail": {"grid": [300, 450], "dtype": "float64",
                        "platform": "cpu", "backend": "xla_session",
                        "devices": 1, "session": True,
                        "warm_start": True}}, source)

    healthy = [rec(32.0, f"b{i}") for i in range(4)]
    verdict = regress.evaluate(healthy + [rec(6.0, "dropped")])
    assert "dropped" in verdict["regressions"]
    verdict = regress.evaluate(healthy + [rec(60.0, "faster")])
    assert not verdict["regressions"]  # faster never alarms
