"""Fused Pallas kernel tests (SURVEY §7 step 5).

The pure-JAX ops are the framework's reference implementation — the role
stage4's retained CPU fallbacks played (``stage4:…cu:198-226``); these tests
A/B the Pallas path against them, on CPU via interpret mode (the kernels
themselves are what runs on TPU — same trace, different executor).
"""

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.ops import pallas_cg
from poisson_tpu.ops.pallas_cg import HALO, build_canvases, pallas_cg_solve
from poisson_tpu.ops.stencil import apply_A
from poisson_tpu.solvers.pcg import host_fields64, pcg_solve


@pytest.mark.parametrize(
    "M,N,bm",
    [
        (40, 40, 16),     # square, interior 39 not divisible by bm
        (80, 120, 16),    # rectangular
        (40, 40, None),   # auto bm (larger than the grid)
    ],
)
def test_full_solve_parity_vs_xla_f32(M, N, bm):
    p = Problem(M=M, N=N)
    r_ref = pcg_solve(p, dtype=jnp.float32)
    r_pal = pallas_cg_solve(p, bm=bm)
    assert int(r_pal.iterations) == int(r_ref.iterations)
    np.testing.assert_allclose(
        np.asarray(r_pal.w), np.asarray(r_ref.w), atol=1e-6
    )


def test_canvases_zero_outside_interior():
    p = Problem(M=40, N=40)
    cv, cs, cw, g, rhs, sc2, sc64 = build_canvases(p, 16)
    band = slice(HALO, HALO + p.M - 1)
    for name, arr, interior_cols in [
        ("rhs", rhs, slice(1, p.N)),
        ("sc2", sc2, slice(1, p.N)),
    ]:
        a = np.asarray(arr)
        mask = np.zeros_like(a, bool)
        mask[band, interior_cols] = True
        assert (a[~mask] == 0).all(), name
    # Coefficient canvases: every edge touching ring/guard/pad is zero, so
    # the kernels need no interior masking (module docstring invariant).
    for name, arr in [("cs", cs), ("cw", cw)]:
        a = np.asarray(arr)
        assert np.isfinite(a).all(), name
        assert (a[:HALO] == 0).all(), name              # guard band
        assert (a[HALO + p.M :] == 0).all(), name       # guard/pad rows
        assert (a[HALO:, p.N + 1 :] == 0).all(), name   # pad columns
        assert a[HALO:].any(), name                     # real coefficients exist
    # Edges touching the Dirichlet ring vanish because sc is zero there:
    # row HALO of cs is the i=1 south edge (neighbour is the ring), and
    # column 1 of cw is the j=1 west edge.
    assert (np.asarray(cs)[HALO] == 0).all()
    assert (np.asarray(cw)[:, 1] == 0).all()
    # …while the next edge inward is genuinely nonzero.
    assert np.asarray(cs)[HALO + 1].any()
    assert np.asarray(cw)[:, 2].any()


def test_kernel_a_matches_scaled_operator():
    """Kernel A's stencil (folded-coefficient form, 4 MACs/pt) against the
    flux-form scaled operator sc·A(sc·y) built from ops.stencil."""
    p = Problem(M=24, N=40)
    cv, cs, cw, g, rhs, sc2, sc64 = build_canvases(p, 8)
    rng = np.random.RandomState(0)

    y_grid = np.zeros((p.M + 1, p.N + 1))
    y_grid[1:-1, 1:-1] = rng.rand(p.M - 1, p.N - 1)

    z = np.zeros((cv.rows, cv.cols), np.float32)
    z[HALO : HALO + p.M - 1, : p.N + 1] = y_grid[1 : p.M, :]
    z = jnp.asarray(z)
    zero = jnp.zeros_like(z)
    beta = jnp.zeros((1, 1), jnp.float32)

    pn, ap, denom = pallas_cg.direction_and_stencil(
        cv, beta, z, zero, cs, cw, g, interpret=True
    )

    a64, b64, _, sc = host_fields64(p, True)
    want = sc * apply_A(sc * y_grid, a64, b64, p.h1, p.h2)
    got = np.asarray(ap)[HALO : HALO + p.M - 1, : p.N + 1]
    np.testing.assert_allclose(got, want[1:-1, :], atol=1e-5)
    # and the per-strip dot partials sum to ⟨Ap, p⟩ (unweighted)
    assert denom.shape == (cv.nb, 1)
    np.testing.assert_allclose(
        float(denom.sum()), float((want[1:-1] * y_grid[1:-1]).sum()), rtol=1e-5
    )


def test_degenerate_direction_stops_cleanly():
    """Zero RHS ⇒ zr=0, first denom=0 ⇒ degenerate guard: solver must stop
    after one iteration with w=0, not NaN."""
    p = Problem(M=16, N=16, max_iter=5)
    cv, cs, cw, g, rhs, sc2, sc64 = build_canvases(p, 8)
    s = pallas_cg._fused_solve(
        p, cv, True, False, False, cs, cw, g, jnp.zeros_like(rhs), sc2
    )
    assert int(s.k) == 1
    assert bool(s.done)
    assert np.isfinite(np.asarray(s.w)).all()
    assert (np.asarray(s.w) == 0).all()


@pytest.mark.parametrize(
    "M,N,bm,bn",
    [
        (40, 40, 16, 128),    # ncb=1: guards exercised, single block
        (40, 300, 16, 128),   # ncb=3: interior columns cross block seams
        (80, 300, None, 256), # auto bm, uneven last block (301 into 2x256)
    ],
)
def test_column_blocked_solve_parity(M, N, bm, bn):
    """The column-blocked (2D-grid) canvas must reproduce the full-width
    fused path: same iteration count, same solution to fp32 tolerance
    (partial-sum tree shape differs, so bitwise equality is not expected)."""
    p = Problem(M=M, N=N)
    r_full = pallas_cg_solve(p)
    r_blk = pallas_cg_solve(p, bm=bm, bn=bn)
    assert int(r_blk.iterations) == int(r_full.iterations)
    np.testing.assert_allclose(
        np.asarray(r_blk.w), np.asarray(r_full.w), atol=1e-6
    )


def test_column_blocked_golden_40x40():
    r = pallas_cg_solve(Problem(M=40, N=40), bm=16, bn=128)
    assert int(r.iterations) == 50


def test_auto_blocking_on_degenerate_width():
    """A canvas too wide for sane full-width strips auto-selects column
    blocking; explicit bm, explicit bn, and the bn=0 force-full-width
    sentinel all win over the auto pick."""
    from poisson_tpu.ops.pallas_cg import canvas_spec

    wide = Problem(M=64, N=20000)
    cv = canvas_spec(wide)
    assert cv.cg == 128 and cv.bm >= 64, cv
    assert canvas_spec(wide, bm=8).cg == 0          # explicit bm: full width
    assert canvas_spec(wide, bn=1024).bn == 1024    # explicit bn honored
    assert canvas_spec(wide, bn=0).cg == 0          # sentinel: full width
    # Published grids keep their proven full-width geometry.
    assert canvas_spec(Problem(M=2400, N=3200)).cg == 0
    # Small-M grids: bm is capped by owned rows, not width — no blocking.
    assert canvas_spec(Problem(M=16, N=40)).cg == 0


def test_checkpoint_layout_survives_auto_blocking():
    """The portable checkpoint path hard-codes the full-width column
    layout; it must keep working (and round-trip) on a grid whose default
    solve auto-blocks."""
    import tempfile

    from poisson_tpu.ops.pallas_cg import (
        canvas_spec, pallas_cg_solve, pallas_cg_solve_checkpointed,
    )

    wide = Problem(M=24, N=17000, max_iter=6)
    assert canvas_spec(wide).cg == 128              # default solve blocks
    with tempfile.TemporaryDirectory() as d:
        got = pallas_cg_solve_checkpointed(wide, f"{d}/ck.npz", chunk=3)
    ref = pallas_cg_solve(wide, bn=0)
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), atol=1e-6
    )


def test_checkpoint_portable_across_canvas_geometries(tmp_path):
    """A checkpoint written from a column-blocked canvas resumes on the
    full-width canvas and matches the one-shot solve: the portable format
    is the full-grid state, independent of canvas geometry."""
    import dataclasses

    from poisson_tpu.ops.pallas_cg import pallas_cg_solve_checkpointed

    p = Problem(M=40, N=300)
    capped = dataclasses.replace(p, max_iter=20)
    ck = str(tmp_path / "ck.npz")
    part = pallas_cg_solve_checkpointed(capped, ck, chunk=7, bn=256)
    assert int(part.iterations) == 20
    got = pallas_cg_solve_checkpointed(p, ck, chunk=7, bn=0)
    ref = pallas_cg_solve(p, bn=0)
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), atol=1e-6
    )


@pytest.mark.slow
def test_column_blocked_golden_400x600():
    """Blocked path at a published grid with real multi-block seams
    (601 content cols → 3 × bn=256): golden count exact."""
    r = pallas_cg_solve(Problem(M=400, N=600), bn=256)
    assert int(r.iterations) == 546


def test_parallel_grid_matches_sequential():
    """The parallel strip-grid option must be a pure scheduling hint: same
    iterate sequence, bit-identical solution (per-strip partials are
    tree-summed the same way either way). On non-megacore devices (this
    CPU run included) it must stay silent — the megacore caveat warning
    is device-gated (round-4 advisor finding + review)."""
    p = Problem(M=40, N=40)
    r_seq = pallas_cg_solve(p)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        r_par = pallas_cg_solve(p, parallel=True)
    assert int(r_par.iterations) == int(r_seq.iterations) == 50
    np.testing.assert_array_equal(np.asarray(r_par.w), np.asarray(r_seq.w))


def test_megacore_predicate():
    """The caveat warning fires exactly on megacore parts: two TensorCores
    fused behind one device (v4, v5p) — not on single-core lite parts, not
    on per-core-device v2/v3, not off-TPU. Real libtpu device_kind strings
    include the bare 'TPU v4'/'TPU v5' spellings (v5p has been reported as
    'TPU v5', with no 'p') and the lite parts' 'TPU v5 lite'/'TPU v5e'."""
    from poisson_tpu.ops.pallas_cg import _is_megacore
    assert _is_megacore("tpu", "TPU v4")
    assert _is_megacore("tpu", "TPU v5p")
    assert _is_megacore("tpu", "TPU v5")       # how libtpu reports v5p
    assert not _is_megacore("tpu", "TPU v5 lite")
    assert not _is_megacore("tpu", "TPU v5e")
    assert not _is_megacore("tpu", "TPU v5litepod-8")
    assert not _is_megacore("tpu", "TPU v6e")
    assert not _is_megacore("tpu", "TPU v3")
    assert not _is_megacore("cpu", "cpu")


def test_megacore_parallel_partials_warns(monkeypatch):
    """On a (faked) megacore device the parallel-grid + partial-output
    combination announces the unverified cross-core write-back. Exercised
    at the _resolve_serial unit — a full solve may hit the jit cache from
    an earlier parallel=True trace and never re-run the resolution."""
    monkeypatch.setattr(pallas_cg, "_is_megacore_device", lambda: True)
    with pytest.warns(RuntimeWarning, match="megacore"):
        assert pallas_cg._resolve_serial(None, True) is False
    with warnings.catch_warnings():  # serial path never uses partials
        warnings.simplefilter("error")
        with pytest.raises(ValueError):
            pallas_cg._resolve_serial(True, True)


def test_gate_is_bit_exact():
    p = Problem(M=40, N=40)
    r1 = pallas_cg_solve(p)
    r2 = pallas_cg_solve(p, rhs_gate=jnp.float32(1.0))
    assert int(r1.iterations) == int(r2.iterations)
    assert np.array_equal(np.asarray(r1.w), np.asarray(r2.w))


@pytest.mark.slow
def test_serial_kahan_reduce_layout_matches_partials():
    """POISSON_TPU_SERIAL_REDUCE=1 switches the reduction partials from
    per-strip (nb, 1) SMEM rows to one Kahan-compensated SMEM cell (the
    layout hardware-proven in round 2). Import-frozen, so the variant runs
    in a subprocess; it must reproduce the golden counts and the default
    layout's L2 on the single-device, column-blocked, sharded-fused, and
    sharded-CA paths."""
    import json
    import os
    import pathlib
    import subprocess
    import sys

    code = r"""
import json
from poisson_tpu.utils.platform import honor_jax_platforms_env
honor_jax_platforms_env()   # config beats env: re-assert JAX_PLATFORMS=cpu
from poisson_tpu.config import Problem
from poisson_tpu.ops.pallas_cg import pallas_cg_solve, SERIAL_REDUCE
from poisson_tpu.analysis import l2_error_host
assert SERIAL_REDUCE
out = {}
p = Problem(M=400, N=600)
r = pallas_cg_solve(p)
out["single"] = [int(r.iterations), l2_error_host(p, r.w)]
r = pallas_cg_solve(p, bn=256)
out["blocked"] = [int(r.iterations), l2_error_host(p, r.w)]
import jax
from poisson_tpu.parallel import make_solver_mesh
from poisson_tpu.parallel.pallas_sharded import pallas_cg_solve_sharded
from poisson_tpu.parallel.pallas_ca_sharded import ca_cg_solve_sharded
mesh = make_solver_mesh(jax.devices()[:4], grid=(2, 2))
r = pallas_cg_solve_sharded(Problem(M=40, N=40), mesh)
out["sharded_2x2"] = [int(r.iterations)]
r = ca_cg_solve_sharded(Problem(M=40, N=40), mesh)
out["ca_sharded_2x2"] = [int(r.iterations)]
print(json.dumps(out))
"""
    env = dict(os.environ)
    env["POISSON_TPU_SERIAL_REDUCE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()
    root = pathlib.Path(__file__).resolve().parents[1]
    env["PYTHONPATH"] = os.pathsep.join(
        [str(root)] + [p for p in [env.get("PYTHONPATH", "")] if p]
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=root, env=env,
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    got = json.loads(proc.stdout.strip().splitlines()[-1])
    assert got["single"][0] == 546
    assert got["blocked"][0] == 546
    assert got["sharded_2x2"][0] == 50
    assert got["ca_sharded_2x2"][0] == 50
    assert got["single"][1] < 4e-4 and got["blocked"][1] < 4e-4


def test_serial_reduce_param_in_process():
    """The threaded ``serial`` knob: in-process A/B against the default
    layout (distinct jit keys), and the contradictory serial+parallel
    combination raises instead of silently preferring one."""
    p = Problem(M=40, N=40)
    r_def = pallas_cg_solve(p, serial=False)   # explicit: env could say 1
    r_ser = pallas_cg_solve(p, serial=True)
    assert int(r_ser.iterations) == int(r_def.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(r_ser.w), np.asarray(r_def.w), rtol=0, atol=5e-6
    )
    with pytest.raises(ValueError, match="parallel"):
        pallas_cg_solve(p, serial=True, parallel=True)
