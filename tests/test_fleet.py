"""Durable solve fleet: supervised workers, the crash-safe request
journal, and recovery that preserves the ledger invariant (tier-1, CPU;
-m fleet).

Worker faults (kill, hang, repeated poison) are injected through the
service's ``worker_fault`` seam under a virtual clock, so quarantine,
restart-through-warm-up, and recovery are deterministic. Journal tests
assert replay truth from the file — CRC-sealed records, torn tails
skipped audibly, exactly one outcome per request across a crash —
including a real subprocess kill/restart drill (exit 75, the PR 1
preemption convention) whose invariant is read from the two emitted
``serve.*`` snapshots.
"""

import json
import os
import subprocess
import sys

import pytest

from poisson_tpu.config import Problem
from poisson_tpu.obs import metrics
from poisson_tpu.serve import (
    ERROR_INTERNAL,
    ERROR_TRANSIENT,
    FleetPolicy,
    OUTCOME_ERROR,
    RetryPolicy,
    SCHED_CONTINUOUS,
    DegradationPolicy,
    ServicePolicy,
    SolveJournal,
    SolveRequest,
    SolveService,
    WORKER_DEAD,
    WORKER_RUNNING,
    replay_journal,
)
from poisson_tpu.testing.chaos import VirtualClock
from poisson_tpu.testing.faults import (
    worker_hang_fault,
    worker_kill_fault,
)

pytestmark = pytest.mark.fleet

P40 = Problem(M=40, N=40)          # converges in 50 iterations (golden)


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    yield
    metrics.reset()


def _quiet():
    return DegradationPolicy(shrink_padding_at=9.0, cap_iterations_at=9.0,
                             downshift_precision_at=9.0)


def _fleet_service(workers=2, *, scheduling="drain", worker_fault=None,
                   journal=None, fleet_kw=None, **policy_kw):
    vc = VirtualClock()
    policy_kw.setdefault("capacity", 16)
    policy_kw.setdefault("max_batch", 4)
    policy_kw.setdefault("degradation", _quiet())
    policy_kw.setdefault(
        "retry", RetryPolicy(max_attempts=3, backoff_base=0.05,
                             backoff_cap=0.1))
    fk = {"workers": workers, "quarantine_seconds": 0.02,
          "recovery_backoff": 0.05}
    fk.update(fleet_kw or {})
    svc = SolveService(
        ServicePolicy(scheduling=scheduling, fleet=FleetPolicy(**fk),
                      **policy_kw),
        clock=vc, sleep=vc.sleep, seed=0, worker_fault=worker_fault,
        journal=journal,
    )
    return svc, vc


# -- worker lifecycle ----------------------------------------------------


def test_worker_kill_mid_dispatch_recovers_to_survivors():
    svc, _ = _fleet_service(worker_fault=worker_kill_fault({0}))
    for i in range(4):
        svc.submit(SolveRequest(request_id=f"r{i}", problem=P40,
                                rhs_gate=1.0 + i / 10))
    outs = {o.request_id: o for o in svc.drain()}
    assert all(o.converged and o.attempts == 2 for o in outs.values())
    assert metrics.get("serve.fleet.quarantines") == 1
    assert metrics.get("serve.fleet.recovered_requests") == 4
    # Mutual taint: the four recovered requests never co-batch again,
    # so the survivors ran them as four separate dispatches.
    assert metrics.get("serve.requeued.isolated") == 4
    assert svc.stats()["lost"] == 0


def test_killed_worker_restarts_through_warmup_and_serves_again():
    svc, vc = _fleet_service(worker_fault=worker_kill_fault({0}))
    for i in range(4):
        svc.submit(SolveRequest(request_id=f"a{i}", problem=P40,
                                rhs_gate=1.0 + i / 10))
    svc.drain()
    assert metrics.get("serve.fleet.restarts") >= 1
    assert metrics.get("serve.fleet.warmup_solves") >= 1
    assert all(s == WORKER_RUNNING
               for s in svc.stats()["workers"].values())
    # The restarted worker takes traffic again (kill budget spent).
    for i in range(4):
        svc.submit(SolveRequest(request_id=f"b{i}", problem=P40,
                                rhs_gate=1.2 + i / 10))
    outs = svc.drain()
    assert all(o.converged and o.attempts == 1 for o in outs)


def test_worker_kill_in_continuous_mode_recovers_lane_occupants():
    svc, _ = _fleet_service(scheduling=SCHED_CONTINUOUS, max_batch=2,
                            refill_chunk=10,
                            worker_fault=worker_kill_fault({0}))
    for i in range(3):
        svc.submit(SolveRequest(request_id=f"l{i}", problem=P40,
                                rhs_gate=1.0 + i / 10))
    outs = {o.request_id: o for o in svc.drain()}
    assert len(outs) == 3 and all(o.converged for o in outs.values())
    assert metrics.get("serve.fleet.quarantines") == 1
    assert metrics.get("serve.fleet.recovered_requests") >= 1
    assert svc.stats()["lost"] == 0


def test_worker_hang_is_caught_by_the_heartbeat_watchdog():
    svc, vc = _fleet_service(fleet_kw={"heartbeat_timeout": 0.2})
    # The hang needs the service's own clock, so it is wired post-hoc.
    svc._worker_fault = worker_hang_fault({0}, 0.5, vc.advance)
    for i in range(3):
        svc.submit(SolveRequest(request_id=i, problem=P40,
                                rhs_gate=1.0 + i / 10))
    outs = svc.drain()
    assert all(o.converged for o in outs)
    assert metrics.get("watchdog.stalls") >= 1
    assert metrics.get("serve.fleet.hangs") >= 1
    assert metrics.get("serve.fleet.quarantines") == 1
    assert svc.stats()["lost"] == 0


def test_slow_but_returning_step_is_quarantined_post_hoc():
    """A step that overruns the heartbeat timeout but RETURNS must
    still draw a stall verdict: its outcomes stand, but the worker is
    quarantined before taking more traffic (the post-step check
    measures from the start-of-step beat — completion must not reset
    the baseline)."""
    svc, vc = _fleet_service(fleet_kw={"heartbeat_timeout": 0.2})
    slow = {"armed": True}

    def crawl(requests, attempts):
        if slow["armed"]:
            slow["armed"] = False
            vc.advance(5.0)          # way past the 0.2s heartbeat

    svc._dispatch_fault = crawl
    for i in range(2):
        svc.submit(SolveRequest(request_id=i, problem=P40,
                                rhs_gate=1.0 + i / 10))
    outs = svc.drain()
    assert all(o.converged and o.attempts == 1 for o in outs)
    assert metrics.get("watchdog.stalls") >= 1
    assert metrics.get("serve.fleet.hangs") >= 1
    assert metrics.get("serve.fleet.quarantines") == 1
    assert svc.stats()["lost"] == 0


def test_str_colliding_ids_stay_distinct_without_recovery():
    """int 1 and string \"1\" are different request ids outside
    recovery — the journal's str-spelling guard must not conflate
    them in a journal-less service."""
    vc = VirtualClock()
    svc = SolveService(ServicePolicy(degradation=_quiet()),
                       clock=vc, sleep=vc.sleep, seed=0)
    svc.submit(SolveRequest(request_id=1, problem=P40))
    svc.submit(SolveRequest(request_id="1", problem=P40, rhs_gate=1.1))
    outs = svc.drain()
    assert len(outs) == 2 and all(o.converged for o in outs)
    assert metrics.get("serve.admitted") == 2


def test_restart_budget_exhaustion_kills_the_worker_for_good():
    svc, _ = _fleet_service(
        worker_fault=worker_kill_fault({0}, kills_per_worker=99),
        fleet_kw={"max_restarts": 1})
    for i in range(6):
        svc.submit(SolveRequest(request_id=i, problem=P40,
                                rhs_gate=1.0 + i / 10))
    outs = svc.drain()
    assert all(o.converged for o in outs)        # survivors carried it
    assert svc.stats()["workers"][0] == WORKER_DEAD
    assert metrics.get("serve.fleet.worker_deaths") == 1
    assert svc.stats()["lost"] == 0


def test_total_fleet_loss_fails_pending_with_typed_internal_errors():
    svc, _ = _fleet_service(
        workers=2,
        worker_fault=worker_kill_fault({0, 1}, kills_per_worker=99),
        fleet_kw={"max_restarts": 0})
    for i in range(3):
        svc.submit(SolveRequest(request_id=i, problem=P40))
    outs = svc.drain()
    assert len(outs) == 3
    assert all(o.kind == OUTCOME_ERROR for o in outs)
    # The first batch dies with the workers (transient after retries);
    # whatever was still queued when the fleet died is failed internal.
    assert {o.error_type for o in outs} <= {ERROR_TRANSIENT,
                                            ERROR_INTERNAL}
    assert svc.stats()["lost"] == 0 and svc.stats()["pending"] == 0


def test_sticky_routing_prefers_the_worker_with_the_executable():
    svc, _ = _fleet_service(workers=2)
    for i in range(8):
        svc.submit(SolveRequest(request_id=i, problem=P40,
                                rhs_gate=1.0 + i / 10))
        svc.drain()
    # After the first dispatch gave one worker the cohort, later heads
    # route to it: hits dominate once sticky state exists.
    assert metrics.get("serve.fleet.sticky_hits") >= 1


def test_single_worker_fleet_is_the_classic_service():
    svc, _ = _fleet_service(workers=1)
    for i in range(3):
        svc.submit(SolveRequest(request_id=i, problem=P40,
                                rhs_gate=1.0 + i / 10))
    outs = svc.drain()
    assert all(o.converged and o.attempts == 1 for o in outs)
    assert metrics.get("serve.fleet.quarantines") == 0
    assert svc.stats()["breakers"]                 # cohort-keyed, no @w
    assert all("@" not in k for k in svc.stats()["breakers"])


# -- idempotent submission (dedup) --------------------------------------


def test_dedup_returns_original_outcome_and_never_double_admits():
    vc = VirtualClock()
    svc = SolveService(ServicePolicy(dedup=True, degradation=_quiet()),
                       clock=vc, sleep=vc.sleep, seed=0)
    assert svc.submit(SolveRequest(request_id="x", problem=P40)) is None
    assert svc.submit(SolveRequest(request_id="x", problem=P40)) is None
    (out,) = svc.drain()
    dup = svc.submit(SolveRequest(request_id="x", problem=P40))
    assert dup is out and dup.converged
    assert metrics.get("serve.dedup.hits") == 2
    assert metrics.get("serve.admitted") == 1
    assert svc.stats()["lost"] == 0


def test_dedup_off_keeps_the_loud_value_error():
    vc = VirtualClock()
    svc = SolveService(ServicePolicy(degradation=_quiet()),
                       clock=vc, sleep=vc.sleep, seed=0)
    svc.submit(SolveRequest(request_id="x", problem=P40))
    with pytest.raises(ValueError, match="duplicate request_id"):
        svc.submit(SolveRequest(request_id="x", problem=P40))


# -- the write-ahead journal --------------------------------------------


def test_journal_records_are_crc_sealed_and_replay_to_the_ledger(tmp_path):
    path = str(tmp_path / "serve.journal")
    vc = VirtualClock()
    journal = SolveJournal(path, clock=vc)
    svc = SolveService(ServicePolicy(degradation=_quiet()),
                       clock=vc, sleep=vc.sleep, seed=0, journal=journal)
    for i in range(3):
        svc.submit(SolveRequest(request_id=f"j{i}", problem=P40,
                                rhs_gate=1.0 + i / 10))
    svc.drain()
    journal.close()
    import zlib

    for line in open(path).read().splitlines():
        rec = json.loads(line)
        crc = rec.pop("crc32")
        blob = json.dumps(rec, sort_keys=True, default=str)
        assert zlib.crc32(blob.encode()) & 0xFFFFFFFF == crc
    replay = replay_journal(path)
    assert replay.submitted == 3
    assert sorted(replay.outcomes) == ["j0", "j1", "j2"]
    assert not replay.pending and not replay.duplicate_outcomes
    assert replay.lost == 0


def test_replay_reconstructs_pending_with_taint_and_attempts(tmp_path):
    path = str(tmp_path / "serve.journal")
    vc = VirtualClock()
    journal = SolveJournal(path, clock=vc)
    svc = SolveService(
        ServicePolicy(scheduling=SCHED_CONTINUOUS, max_batch=2,
                      refill_chunk=10, degradation=_quiet()),
        clock=vc, sleep=vc.sleep, seed=0, journal=journal)
    for i in range(2):
        svc.submit(SolveRequest(request_id=f"p{i}", problem=P40,
                                rhs_gate=1.0 + i / 10,
                                deadline_seconds=3600.0))
    svc.pump()                       # both lane-resident, mid-flight
    journal.close()                  # crash
    replay = replay_journal(path)
    assert len(replay.pending) == 2
    for pend in replay.pending:
        assert pend.in_flight and pend.attempts == 1
        assert pend.request.problem == P40
        assert pend.request.deadline_seconds == 3600.0
    taints = {p.request.request_id: p.taint for p in replay.pending}
    assert taints["p0"] == {"p1"} and taints["p1"] == {"p0"}


def test_recovered_requests_drain_without_double_admission(tmp_path):
    path = str(tmp_path / "serve.journal")
    vc = VirtualClock()
    policy = ServicePolicy(scheduling=SCHED_CONTINUOUS, max_batch=2,
                           refill_chunk=10, degradation=_quiet())
    journal_a = SolveJournal(path, clock=vc)
    svc_a = SolveService(policy, clock=vc, sleep=vc.sleep, seed=0,
                         journal=journal_a)
    for i in range(4):
        svc_a.submit(SolveRequest(request_id=f"c{i}", problem=P40,
                                  rhs_gate=1.0 + i / 10))
    while len(svc_a.outcomes()) < 2:
        svc_a.pump()
    journal_a.close()                # crash with 2 done, 2 in flight
    journal_b = SolveJournal(path, clock=vc)
    svc_b = SolveService.recover(journal_b, policy, clock=vc,
                                 sleep=vc.sleep, seed=0)
    assert svc_b.recovery.submitted == 4
    assert len(svc_b.recovery.pending) == 2
    outs = svc_b.drain()
    journal_b.close()
    assert len(outs) == 2 and all(o.converged for o in outs)
    stats = svc_b.stats()
    assert stats["recovered"] == 2 and stats["lost"] == 0
    # Merged-counter invariant across the "crash": one registry played
    # both processes, so admitted(4) == completed(4), recovered NOT
    # re-admitted.
    assert metrics.get("serve.admitted") == 4
    assert metrics.get("serve.completed") == 4
    assert metrics.get("serve.recovered") == 2
    final = replay_journal(path)
    assert sorted(final.outcomes) == [f"c{i}" for i in range(4)]
    assert not final.duplicate_outcomes and not final.pending


def test_requeue_taint_survives_replay(tmp_path):
    """Mutual taint established BEFORE a crash (a poisoned batch
    requeued into backoff) must survive the replay — never-co-batch-
    again is forever, not per-process."""
    from poisson_tpu.testing.faults import poison_batch_fault

    path = str(tmp_path / "serve.journal")
    vc = VirtualClock()
    journal = SolveJournal(path, clock=vc)
    svc = SolveService(
        ServicePolicy(degradation=_quiet(),
                      retry=RetryPolicy(max_attempts=3)),
        clock=vc, sleep=vc.sleep, seed=0, journal=journal,
        dispatch_fault=poison_batch_fault({"p"}))
    svc.submit(SolveRequest(request_id="p", problem=P40))
    svc.submit(SolveRequest(request_id="q", problem=P40, rhs_gate=1.1))
    svc.pump()                       # batch dies; both back off tainted
    journal.close()                  # crash during backoff
    replay = replay_journal(path)
    taints = {pend.request.request_id: pend.taint
              for pend in replay.pending}
    assert taints == {"p": {"q"}, "q": {"p"}}


def test_recovered_ids_guard_resubmission_of_the_original_type(tmp_path):
    """The journal stringifies ids: a client retrying with the original
    (int) id after recovery must still hit the dedup guard — never a
    double admission."""
    path = str(tmp_path / "serve.journal")
    vc = VirtualClock()
    journal_a = SolveJournal(path, clock=vc)
    svc_a = SolveService(ServicePolicy(degradation=_quiet()),
                         clock=vc, sleep=vc.sleep, seed=0,
                         journal=journal_a)
    svc_a.submit(SolveRequest(request_id=7, problem=P40))
    journal_a.close()                # crash with 7 still queued
    journal_b = SolveJournal(path, clock=vc)
    svc_b = SolveService.recover(
        journal_b, ServicePolicy(dedup=True, degradation=_quiet()),
        clock=vc, sleep=vc.sleep, seed=0)
    assert svc_b.submit(SolveRequest(request_id=7, problem=P40)) is None
    assert metrics.get("serve.dedup.hits") == 1
    assert metrics.get("serve.admitted") == 1    # the original only
    outs = svc_b.drain()
    journal_b.close()
    assert len(outs) == 1 and outs[0].converged
    assert svc_b.stats()["lost"] == 0


def test_torn_tail_and_crc_corruption_are_skipped_audibly(tmp_path):
    path = str(tmp_path / "serve.journal")
    vc = VirtualClock()
    journal = SolveJournal(path, clock=vc)
    svc = SolveService(ServicePolicy(degradation=_quiet()),
                       clock=vc, sleep=vc.sleep, seed=0, journal=journal)
    svc.submit(SolveRequest(request_id="torn", problem=P40))
    journal.close()                  # crash before any dispatch
    with open(path, "a") as fh:
        # A sealed-looking outcome with a WRONG crc: must not mark the
        # request terminated. Then a half-written line.
        fh.write('{"kind": "outcome", "outcome": "result", '
                 '"request_id": "torn", "seq": 9, "t": 1.0, '
                 '"crc32": 1}\n')
        fh.write('{"seq": 10, "ki')
    replay = replay_journal(path)
    assert replay.torn_records == 2
    assert len(replay.torn_detail) == 2
    assert not replay.outcomes       # the fake outcome was not trusted
    assert [p.request.request_id for p in replay.pending] == ["torn"]
    assert metrics.get("serve.journal.torn_records") >= 2
    # The invariant still closes: recover and drain.
    journal_b = SolveJournal(path, clock=vc)
    svc_b = SolveService.recover(
        journal_b, ServicePolicy(degradation=_quiet()),
        clock=vc, sleep=vc.sleep, seed=0)
    (out,) = svc_b.drain()
    journal_b.close()
    assert out.converged and svc_b.stats()["lost"] == 0


def test_crash_restart_subprocess_drill(tmp_path):
    """Kill ``python -m poisson_tpu serve`` mid-run (exit 75), restart
    against the journal: the invariant closes across the boundary from
    the two emitted metrics snapshots, zero lost, zero duplicated."""
    journal = str(tmp_path / "serve.journal")
    a_metrics = str(tmp_path / "a.json")
    b_metrics = str(tmp_path / "b.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    base = [sys.executable, "-m", "poisson_tpu", "serve", "40", "40",
            "--continuous", "--refill-chunk", "10", "--max-batch", "2",
            "--journal", journal, "--seed", "0"]
    a = subprocess.run(base + ["--requests", "6", "--kill-after", "2",
                               "--metrics-out", a_metrics],
                       capture_output=True, text=True, timeout=240,
                       env=env)
    assert a.returncode == 75, a.stderr[-500:]
    b = subprocess.run(base + ["--requests", "0", "--recover",
                               "--json", "--metrics-out", b_metrics],
                       capture_output=True, text=True, timeout=240,
                       env=env)
    assert b.returncode == 0, b.stderr[-500:]
    record = json.loads(b.stdout.strip().splitlines()[-1])
    assert record["lost"] == 0 and record["recovered"] > 0
    ca = json.load(open(a_metrics))["counters"]
    cb = json.load(open(b_metrics))["counters"]

    def terminated(c):
        return (c.get("serve.completed", 0) + c.get("serve.errors", 0)
                + c.get("serve.shed", 0))

    admitted = ca.get("serve.admitted", 0) + cb.get("serve.admitted", 0)
    assert admitted == 6
    assert terminated(ca) + terminated(cb) == 6
    assert cb.get("serve.recovered") == 6 - terminated(ca)
    final = replay_journal(journal)
    assert sorted(final.outcomes) == [str(i) for i in range(6)]
    assert not final.duplicate_outcomes and not final.pending


# -- regression-sentinel cohorting --------------------------------------


def test_workers_split_sentinel_cohorts_and_direction_stays_pinned():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from benchmarks import regress

    def rec(value, workers, fault="clean"):
        return regress.record_from_result(
            {"metric": "serve.sustained_solves_per_sec", "value": value,
             "detail": {"grid": [96, 144], "dtype": "float32",
                        "backend": "xla_serve", "devices": 1,
                        "platform": "cpu", "arrival_rate": 60.0,
                        "workers": workers, "fault_load": fault}},
            source=f"w{workers}:{value}")

    # A 4-worker record never cohorts with single-worker baselines:
    # a much-lower churned-fleet number classifies no_baseline, not
    # regression.
    history = [rec(60.0, 1), rec(61.0, 1), rec(59.0, 1)]
    verdict = regress.evaluate(history + [rec(20.0, 4)])
    by_source = {v["source"]: v for v in verdict["records"]}
    assert by_source["w4:20.0"]["classification"] == "no_baseline"
    assert verdict["verdict"] == "ok"
    # Direction pin: sustained solves/sec stays higher-is-better inside
    # a workers cohort — a 2x drop against same-workers history pages.
    fleet_history = [rec(40.0, 4), rec(41.0, 4), rec(39.0, 4)]
    slowed = regress.evaluate(fleet_history + [rec(19.0, 4)])
    assert slowed["verdict"] == "regression"
    # And workers=None legacy records are their own cohort.
    legacy = regress.record_from_result(
        {"metric": "serve.sustained_solves_per_sec", "value": 55.0,
         "detail": {"grid": [96, 144], "dtype": "float32",
                    "backend": "xla_serve", "devices": 1,
                    "platform": "cpu", "arrival_rate": 60.0,
                    "fault_load": "clean"}}, source="legacy")
    assert regress.cohort_key(legacy) != regress.cohort_key(rec(55.0, 1))


# -- flight-recorder attribution ----------------------------------------


def test_recovery_points_and_worker_attrs_ride_the_flight_trace(tmp_path):
    from poisson_tpu import obs
    from poisson_tpu.obs import flight
    from poisson_tpu.obs.trace import load_events

    obs.configure(trace_dir=str(tmp_path))
    svc, _ = _fleet_service(worker_fault=worker_kill_fault({0}))
    svc.submit(SolveRequest(request_id="traced", problem=P40))
    (out,) = svc.drain()
    obs.finalize()
    events = load_events(str(tmp_path))
    tid, recs = flight.find_trace(events, request_id="traced")
    assert tid == out.trace_id
    assert not flight.validate_trace(recs)
    points = {flight._field(r, "point") for r in recs
              if r.get("name") == "flight.point"}
    assert {"quarantine", "recovered"} <= points
    resident = [r for r in recs if r.get("name") == "flight.span"
                and flight._field(r, "span") == "lane_resident"]
    assert resident and all(
        flight._field(r, "worker") is not None for r in resident)
    timeline = flight.render_timeline(recs)
    assert "recovered" in timeline and "quarantine" in timeline
    assert "worker=" in timeline
