"""Native C++ oracle: golden counts, OpenMP behaviour, parity with JAX.

The reference's serial/OpenMP stages are native C++ compared empirically
across implementations (SURVEY §4.1); here the native backend and the
JAX/XLA backend are compared *in-process* — same golden iteration counts,
same solution to fp64 round-off.
"""

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.native import build, has_openmp, native_solve
from poisson_tpu.solvers.pcg import pcg_solve


def test_build_produces_library():
    path = build()
    assert path.endswith(".so")


@pytest.mark.parametrize(
    "M,N,weighted,expected",
    [
        (10, 10, False, 17),
        (20, 20, False, 31),
        (40, 40, False, 61),
        (40, 40, True, 50),
    ],
)
def test_native_golden_iterations(M, N, weighted, expected):
    # num_threads=1: exact counts need a fixed reduction order (the default
    # team is machine- and test-order-dependent).
    r = native_solve(Problem(M=M, N=N, weighted_norm=weighted), num_threads=1)
    assert r.iterations == expected
    assert r.diff < 1e-6


def test_native_matches_jax_fp64():
    """Cross-backend equivalence: the reference's only correctness method
    (SURVEY §4.1), automated. Summation order differs (sequential vs XLA
    tree reduction), so parity is to round-off, not bitwise."""
    p = Problem(M=40, N=40)
    rn = native_solve(p, num_threads=1)
    rj = pcg_solve(p)
    assert rn.iterations == int(rj.iterations)
    np.testing.assert_allclose(rn.w, np.asarray(rj.w), rtol=0, atol=1e-10)


def test_native_openmp_thread_counts_agree():
    """The stage1 experiment (thread sweep, same answer): iteration count
    is reduction-order sensitive only within one ulp of delta, so allow ±1;
    solutions must agree to round-off."""
    if not has_openmp():
        pytest.skip("library built without OpenMP")
    p = Problem(M=40, N=40)
    base = native_solve(p, num_threads=1)
    for t in (2, 4):
        r = native_solve(p, num_threads=t)
        assert abs(r.iterations - base.iterations) <= 1
        np.testing.assert_allclose(r.w, base.w, rtol=0, atol=1e-10)


@pytest.mark.slow
def test_native_golden_400x600():
    # 4-thread reduction order is nondeterministic; the count is exact at a
    # fixed order and can flip by one ulp otherwise (see thread-sweep test).
    r = native_solve(Problem(M=400, N=600), num_threads=4)
    assert abs(r.iterations - 546) <= 1


@pytest.mark.parametrize("M,N", [(2, 2), (2, 10), (10, 2), (3, 200)])
def test_edge_grids_agree_with_jax(M, N):
    """Degenerate-direction and iteration-cap semantics on minimal grids:
    tiny interiors exhaust the Krylov space (exact solve) or hit the
    (M-1)(N-1) cap — both backends must stop identically."""
    p = Problem(M=M, N=N)
    rn = native_solve(p, num_threads=1)
    rj = pcg_solve(p)
    assert rn.iterations == int(rj.iterations)
    np.testing.assert_allclose(rn.w, np.asarray(rj.w), rtol=0, atol=1e-10)


@pytest.mark.xslow
@pytest.mark.parametrize(
    "M,N,expected", [(1600, 2400, 1858), (2400, 3200, 2449)]
)
def test_native_golden_largest_grids(M, N, expected):
    """The two largest published grids (BASELINE.md, Этап_4_1213.pdf
    Table 1). ~2-3 min each on CPU."""
    import os

    r = native_solve(Problem(M=M, N=N), num_threads=os.cpu_count())
    assert abs(r.iterations - expected) <= 1
