"""Fused-path (Pallas) checkpoint/resume, single-device and sharded, plus
the cross-backend portability matrix: every fp32 checkpoint (XLA scaled,
fused, sharded, fused-sharded) resumes on every other backend — one
portable .npz format under one fingerprint (no reference analog; the
framework-added subsystem finished across all compute paths)."""

import jax
import numpy as np

from poisson_tpu.config import Problem
from poisson_tpu.ops.pallas_cg import (
    pallas_cg_solve,
    pallas_cg_solve_checkpointed,
)
from poisson_tpu.parallel import (
    make_solver_mesh,
    pallas_cg_solve_sharded,
    pallas_cg_solve_sharded_checkpointed,
    pcg_solve_sharded_checkpointed,
)
from poisson_tpu.solvers.checkpoint import pcg_solve_checkpointed


def test_fused_chunked_equals_oneshot(tmp_path):
    p = Problem(M=40, N=40)
    ref = pallas_cg_solve(p)
    got = pallas_cg_solve_checkpointed(p, str(tmp_path / "ck.npz"), chunk=7)
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_array_equal(np.asarray(got.w), np.asarray(ref.w))
    assert not (tmp_path / "ck.npz").exists()


def test_fused_kill_and_resume(tmp_path):
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    partial = pallas_cg_solve_checkpointed(p.with_(max_iter=20), path, chunk=10)
    assert int(partial.iterations) == 20
    assert (tmp_path / "ck.npz").exists()

    ref = pallas_cg_solve(p)
    resumed = pallas_cg_solve_checkpointed(p, path, chunk=10)
    # The β := 1, p := d − r resume mapping is exact to one ulp per element
    # (ops.pallas_cg module comment) — counts match, values to fp32 noise.
    assert int(resumed.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(resumed.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )
    assert not (tmp_path / "ck.npz").exists()


def test_fused_sharded_chunked_equals_oneshot(tmp_path):
    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices())
    ref = pallas_cg_solve_sharded(p, mesh)
    got = pallas_cg_solve_sharded_checkpointed(
        p, mesh, str(tmp_path / "ck.npz"), chunk=7
    )
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )
    assert not (tmp_path / "ck.npz").exists()


def test_fused_sharded_kill_and_resume(tmp_path):
    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices())
    path = str(tmp_path / "ck.npz")
    partial = pallas_cg_solve_sharded_checkpointed(
        p.with_(max_iter=20), mesh, path, chunk=10
    )
    assert int(partial.iterations) == 20
    ref = pallas_cg_solve_sharded(p, mesh)
    resumed = pallas_cg_solve_sharded_checkpointed(p, mesh, path, chunk=10)
    assert int(resumed.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(resumed.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )


def test_cross_backend_resume_matrix(tmp_path):
    """Partial solves from each fp32 backend resumed by a different one."""
    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices())
    ref = pallas_cg_solve(p)

    # XLA fp32-scaled partial → fused resume.
    path = str(tmp_path / "a.npz")
    pcg_solve_checkpointed(p.with_(max_iter=15), path, chunk=5,
                           dtype="float32")
    got = pallas_cg_solve_checkpointed(p, path, chunk=20)
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )

    # Fused partial → sharded-XLA resume.
    path = str(tmp_path / "b.npz")
    pallas_cg_solve_checkpointed(p.with_(max_iter=15), path, chunk=5)
    got = pcg_solve_sharded_checkpointed(p, mesh, path, chunk=20,
                                         dtype="float32")
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )

    # Fused-sharded partial → single-device XLA resume.
    path = str(tmp_path / "c.npz")
    pallas_cg_solve_sharded_checkpointed(p.with_(max_iter=15), mesh, path,
                                         chunk=5)
    got = pcg_solve_checkpointed(p, path, chunk=20, dtype="float32")
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )
