"""PDE-constrained optimisation with the differentiable solver.

Recover an unknown source term from an observed solution by gradient
descent through the PCG solve (implicit adjoint differentiation — each
gradient is one extra solve, regardless of iteration count):

    JAX_PLATFORMS=cpu python examples/source_identification.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

# Honor JAX_PLATFORMS before any device touch: site hooks registering a
# remote-accelerator plugin override jax.config at interpreter startup
# (config beats env), and a wedged tunnel then hangs the first jax call.
from poisson_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax

jax.config.update("jax_enable_x64", True)  # delta=1e-10 needs fp64 state

import jax.numpy as jnp

from poisson_tpu import Problem
from poisson_tpu.models.fictitious_domain import build_fields
from poisson_tpu.solvers import differentiable_solve

problem = Problem(M=40, N=40, delta=1e-10)
_, _, true_source = build_fields(problem)
observed = differentiable_solve(problem, true_source)


def loss(source):
    w = differentiable_solve(problem, source)
    return jnp.sum((w - observed) ** 2)


source = 0.5 * true_source  # wrong initial guess
for step in range(5):
    value, grad = jax.value_and_grad(loss)(source)
    # Exact line search on the quadratic: t* = |g|^2 / (2 |A^{-1}g|^2).
    ainv_g = differentiable_solve(problem, grad)
    t = jnp.sum(grad * grad) / (2 * jnp.sum(ainv_g * ainv_g) + 1e-30)
    source = source - t * grad
    print(f"step {step}: loss {float(value):.3e}")

print(f"final loss {float(loss(source)):.3e} "
      f"(source recovered to solver tolerance)")
