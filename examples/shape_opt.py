"""Shape optimization as a durable solver session.

Recover an unknown domain offset from an observed solution by gradient
descent on the ellipse parameters, driven through the solve service as
ONE design session: each iteration is a forward solve + an implicit
adjoint solve (:func:`poisson_tpu.solvers.adjoint.shape_gradient`),
the descended ellipse becomes the session's next step — warm-started
from the previous iterate while the move stays inside the validity
bound — and every transition is a journaled, recoverable step boundary:

    JAX_PLATFORMS=cpu python examples/shape_opt.py

Runs in well under a minute on CPU (40x40 grid, 12 descent steps).
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

# Honor JAX_PLATFORMS before any device touch: site hooks registering a
# remote-accelerator plugin override jax.config at interpreter startup
# (config beats env), and a wedged tunnel then hangs the first jax call.
from poisson_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax

jax.config.update("jax_enable_x64", True)  # adjoint solves want fp64

import numpy as np

from poisson_tpu import Problem
from poisson_tpu.geometry import Ellipse
from poisson_tpu.obs import metrics
from poisson_tpu.serve import ServicePolicy, SessionHost, SolveService
from poisson_tpu.solvers.pcg import pcg_solve

# Tight solver tolerance: the adjoint differentiates THROUGH the solve,
# so solver error is gradient noise — keep it well below the descent's
# per-step moves, but above this grid's Krylov breakdown floor (~1e-9).
problem = Problem(M=40, N=40, delta=1e-8)

# The "observed" solution: a solve on the TRUE (unknown) domain — the
# default ellipse shifted right by 0.12 (about 2.5 grid cells).
true_spec = Ellipse(cx=0.12)
target = np.asarray(pcg_solve(problem, geometry=true_spec).w)

svc = SolveService(ServicePolicy(capacity=64))
host = SessionHost(svc)
sess = host.open("shape-opt", problem, kind="design", dtype="float64",
                 geometry=Ellipse(), params={"note": "examples/shape_opt"})
assert sess is not None, "design session was shed on an idle service"

first_loss = None
loss = float("inf")
for it in range(12):
    out, loss, grads = host.design_step(sess, target, lr=20.0)
    if first_loss is None:
        first_loss = loss
    p = sess.design_params
    print(f"step {it}: loss {loss:.3e}  cx {p['cx']:+.4f}  "
          f"({int(out.iterations)} iterations)")

warm_hits = metrics.snapshot()["counters"].get("session.warm.hits", 0)
summary = host.close(sess)
err = abs(sess.design_params["cx"] - true_spec.cx)
print(f"closed: {summary['steps']} steps, slo_good={summary['slo_good']}, "
      f"{warm_hits} warm-started")
print(f"final loss {loss:.3e} (from {first_loss:.3e}), "
      f"center error {err:.4f} (grid cell h1 = {problem.h1:.3f})")
if not (loss < 0.25 * first_loss and err < problem.h1):
    print("shape optimization did NOT converge", file=sys.stderr)
    sys.exit(1)
print("recovered the domain offset to within one grid cell")
