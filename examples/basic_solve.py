"""Minimal usage: solve the reference's flagship problem and report.

    JAX_PLATFORMS=cpu python examples/basic_solve.py   # or on TPU: drop the env var
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

# Honor JAX_PLATFORMS before any device touch: site hooks registering a
# remote-accelerator plugin override jax.config at interpreter startup
# (config beats env), and a wedged tunnel then hangs the first jax call.
from poisson_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

from poisson_tpu import Problem, pcg_solve
from poisson_tpu.analysis import l2_error_vs_analytic

problem = Problem(M=400, N=600)
result = pcg_solve(problem)

print(f"grid {problem.M}x{problem.N}: converged in {int(result.iterations)} "
      f"iterations (golden: 546)")
print(f"final ||dw|| = {float(result.diff):.3e}")
print(f"L2 error vs analytic u=(1-x^2-4y^2)/10: "
      f"{float(l2_error_vs_analytic(problem, result.w)):.3e}")
