"""Distributed solve over every visible device (the stage2/3/4 workload).

On a CPU-only host, emulate a pod slice first:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/distributed_solve.py

On TPU hardware the same script uses the real chips; on a multi-host pod,
call ``poisson_tpu.parallel.multihost.initialize_multihost()`` first (as
the first JAX call) and run one copy per host.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

# Honor JAX_PLATFORMS before any device touch: site hooks registering a
# remote-accelerator plugin override jax.config at interpreter startup
# (config beats env), and a wedged tunnel then hangs the first jax call.
from poisson_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax

from poisson_tpu import Problem
from poisson_tpu.parallel import make_solver_mesh, pcg_solve_sharded

mesh = make_solver_mesh()  # near-square 2D mesh over all devices
problem = Problem(M=400, N=600)
result = pcg_solve_sharded(problem, mesh)

print(f"devices: {len(jax.devices())}  mesh: {dict(mesh.shape)}")
print(f"converged in {int(result.iterations)} iterations (golden: 546), "
      f"||dw|| = {float(result.diff):.3e}")

if jax.devices()[0].platform == "tpu":
    # The fused-kernel distributed path (stage4's configuration).
    from poisson_tpu.parallel import pallas_cg_solve_sharded

    fused = pallas_cg_solve_sharded(problem, mesh)
    print(f"fused Pallas path: {int(fused.iterations)} iterations")

    # The communication-avoiding s=2 pair iteration over the same mesh:
    # ~1.46x less HBM traffic per iteration and one Gram reduction round
    # per PAIR of iterations (parallel.pallas_ca_sharded module doc).
    from poisson_tpu.parallel import ca_cg_solve_sharded

    ca = ca_cg_solve_sharded(problem, mesh)
    print(f"CA s=2 path: {int(ca.iterations)} iterations")
